"""Hardware block-size sweep for the Pallas flash-attention kernels.

Times fwd+bwd of `flash_attention` on the real TPU across block_q/block_k
candidates for the shapes our templates actually run (ViT-B/16 seq 197→256
d64 h12; BERT seq 128; Llama seq 512 GQA), plus the pure-XLA attention as
the thing to beat. Prints a JSON report; run manually when the axon tunnel
claims (VERDICT r02 "weak #3": block sizes never timed on hardware).

Usage: python scripts/tune_attention_tpu.py [--quick]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from rafiki_tpu.ops.attention import _attention_reference, flash_attention


def _time_fn(fn, *args, iters: int = 20) -> float:
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def sweep(shape, causal: bool, blocks, iters: int,
          block_hs=(1,)) -> list[dict]:
    """Each row carries fwd_bwd_ms (train step shape) AND fwd_ms (the
    inference path — no LSE write, the serving regime). ``block_hs``
    adds the multi-head-per-program forward candidates (VERDICT r4
    item 3: amortize per-program grid/DMA overhead at short seq)."""
    b, h, s, d = shape
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, h, s, d), jnp.bfloat16)

    rows = []

    def loss(fn):
        def f(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)
        return jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))

    def fwd_only(fn):
        return jax.jit(lambda q, k, v: fn(q, k, v))

    # the thing to beat: XLA's own attention (what jnp einsum+softmax gives)
    def xla(q, k, v):
        return _attention_reference(q, k, v, 1.0 / (d ** 0.5), causal)

    rows.append({"impl": "xla",
                 "fwd_bwd_ms": _time_fn(loss(xla), q, k, v, iters=iters),
                 "fwd_ms": _time_fn(fwd_only(xla), q, k, v,
                                    iters=iters)})

    for bq, bk in blocks:
        if bq > s * 2 or bk > s * 2:
            continue
        for bh in block_hs:
            if h % bh:
                continue

            def pallas(q, k, v, bq=bq, bk=bk, bh=bh):
                return flash_attention(q, k, v, causal=causal,
                                       block_q=bq, block_k=bk,
                                       block_h=bh, interpret=False)

            name = f"pallas_q{bq}_k{bk}" + (f"_h{bh}" if bh > 1 else "")
            try:
                rows.append({
                    "impl": name,
                    "fwd_bwd_ms": _time_fn(loss(pallas), q, k, v,
                                           iters=iters),
                    "fwd_ms": _time_fn(fwd_only(pallas), q, k, v,
                                       iters=iters)})
            except Exception as e:  # noqa: BLE001 — record, keep sweeping
                rows.append({"impl": name, "error": repr(e)[:120]})
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    assert jax.default_backend() == "tpu", jax.default_backend()
    iters = 10 if args.quick else 30
    blocks = list(itertools.product([128, 256, 512], [128, 256, 512]))
    if args.quick:
        blocks = [(128, 128), (256, 128), (256, 256), (512, 256)]

    report = {}
    # short-seq cases sweep the multi-head grid too (h must divide);
    # long-seq keeps per-head programs (each already does real work)
    cases = {
        # VERDICT r4 item 3's seq set {128, 197, 256, 512, 1k}
        # ViT-B/16: 197 tokens (padded to 256 by the wrapper), 12 heads d64
        "vit_b16_bs32": ((32, 12, 197, 64), False, (1, 2, 4)),
        "vit_b16_bs64": ((64, 12, 197, 64), False, (1, 2, 4)),
        # BERT-base seq128
        "bert_bs32_s128": ((32, 12, 128, 64), False, (1, 2, 4)),
        "s256_bs32": ((32, 12, 256, 64), False, (1, 2, 4)),
        # Llama-style causal seq512 (8 kv heads worth after GQA repeat)
        "llama_bs4_s512": ((4, 32, 512, 128), True, (1, 2)),
        "llama_bs2_s1k": ((2, 32, 1024, 128), True, (1,)),
    }
    if args.quick:
        cases = {k: cases[k] for k in ("vit_b16_bs64", "llama_bs4_s512")}
    for name, (shape, causal, block_hs) in cases.items():
        report[name] = sweep(shape, causal, blocks, iters,
                             block_hs=block_hs)
        ok_rows = [r for r in report[name] if "fwd_bwd_ms" in r]
        best = min(ok_rows, key=lambda r: r["fwd_bwd_ms"])
        best_f = min(ok_rows, key=lambda r: r["fwd_ms"])
        print(f"# {name}: best_train={best['impl']} "
              f"{best['fwd_bwd_ms']:.2f}ms best_infer={best_f['impl']} "
              f"{best_f['fwd_ms']:.2f}ms", flush=True)
    print(json.dumps(report))
    with open(".tune_attn_tpu.json", "w") as f:  # gitignored name
        json.dump(report, f)


if __name__ == "__main__":
    main()
