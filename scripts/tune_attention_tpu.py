"""Hardware block-size sweep for the Pallas flash-attention kernels.

Times fwd+bwd of `flash_attention` on the real TPU across block_q/block_k
candidates for the shapes our templates actually run (ViT-B/16 seq 197→256
d64 h12; BERT seq 128; Llama seq 512 GQA), plus the pure-XLA attention as
the thing to beat. Prints a JSON report; run manually when the axon tunnel
claims (VERDICT r02 "weak #3": block sizes never timed on hardware).

Usage: python scripts/tune_attention_tpu.py [--quick]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from rafiki_tpu.ops.attention import _attention_reference, flash_attention


def _time_fn(fn, *args, iters: int = 20) -> float:
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def sweep(shape, causal: bool, blocks, iters: int) -> list[dict]:
    b, h, s, d = shape
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, h, s, d), jnp.bfloat16)

    rows = []

    def loss(fn):
        def f(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)
        return jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))

    # the thing to beat: XLA's own attention (what jnp einsum+softmax gives)
    xla_fn = loss(lambda q, k, v: _attention_reference(
        q, k, v, 1.0 / (d ** 0.5), causal))
    rows.append({"impl": "xla", "fwd_bwd_ms": _time_fn(
        xla_fn, q, k, v, iters=iters)})

    for bq, bk in blocks:
        if bq > s * 2 or bk > s * 2:
            continue
        fn = loss(lambda q, k, v, bq=bq, bk=bk: flash_attention(
            q, k, v, causal=causal, block_q=bq, block_k=bk,
            interpret=False))
        try:
            ms = _time_fn(fn, q, k, v, iters=iters)
            rows.append({"impl": f"pallas_q{bq}_k{bk}", "fwd_bwd_ms": ms})
        except Exception as e:  # noqa: BLE001 — record and keep sweeping
            rows.append({"impl": f"pallas_q{bq}_k{bk}",
                         "error": repr(e)[:120]})
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    assert jax.default_backend() == "tpu", jax.default_backend()
    iters = 10 if args.quick else 30
    blocks = list(itertools.product([128, 256, 512], [128, 256, 512]))
    if args.quick:
        blocks = [(128, 128), (256, 128), (256, 256), (512, 256)]

    report = {}
    cases = {
        # ViT-B/16: 197 tokens (padded to 256 by the wrapper), 12 heads d64
        "vit_b16_bs32": ((32 * 1, 12, 197, 64), False),
        # BERT-base seq128
        "bert_bs32_s128": ((32, 12, 128, 64), False),
        # Llama-style causal seq512 (8 kv heads worth after GQA repeat)
        "llama_bs4_s512": ((4, 32, 512, 128), True),
    }
    for name, (shape, causal) in cases.items():
        report[name] = sweep(shape, causal, blocks, iters)
        best = min((r for r in report[name] if "fwd_bwd_ms" in r),
                   key=lambda r: r["fwd_bwd_ms"])
        print(f"# {name}: best={best['impl']} "
              f"{best['fwd_bwd_ms']:.2f}ms", flush=True)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
