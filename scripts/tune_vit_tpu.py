"""Hardware throughput experiments for the ViT-B/16 train step.

Times the full adam train step (donated buffers, like bench.py) on the
real TPU across: compute dtype (f32 promote vs bf16), attention impl
(Pallas flash vs pure-XLA), and batch size. Run manually when the axon
tunnel claims; feeds the block-size/MFU work (VERDICT r02 weak #3).

Usage: python scripts/tune_vit_tpu.py [bs ...]
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import optax

import rafiki_tpu.models.vit as vit_mod
from rafiki_tpu.ops.attention import _attention_reference

# ViT-B/16 train-step FLOPs/sample ≈ 3x fwd; fwd ≈ 17.6 GF @ 224
STEP_GFLOP_PER_SAMPLE = 52.8
PEAK_TFLOPS_BF16 = 197.0  # v5e


def build_step(bs: int, dtype, attn: str, remat: bool = False):
    """The ONE ViT-B/16 donated-buffer adam train step every hardware
    experiment measures (this sweep AND scripts/profile_vit_tpu.py —
    a profiled step that silently differs from the benchmarked one
    misdirects the MFU work). ``attn='xla'`` swaps the module's
    attention to the pure-XLA reference — the config that holds the
    r4 throughput record. Returns ``(step, params, opt_state, img,
    lbl, restore)``; call ``restore()`` when done (monkeypatch)."""
    restore = lambda: None  # noqa: E731
    if attn == "xla":
        orig = vit_mod.flash_attention
        vit_mod.flash_attention = (
            lambda q, k, v, *a, **kw: _attention_reference(
                q, k, v, 1.0 / (q.shape[-1] ** 0.5), False))

        def restore():
            vit_mod.flash_attention = orig

    module = vit_mod.ViT(patch_size=16, hidden_dim=768, depth=12,
                         n_heads=12, mlp_dim=3072, n_classes=1000,
                         dtype=dtype, remat=remat)
    tx = optax.adam(1e-3)
    img = jnp.zeros((bs, 224, 224, 3), jnp.bfloat16)
    lbl = jnp.zeros((bs,), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), img[:1])["params"]
    opt_state = tx.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = module.apply({"params": p}, xb)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), yb))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step, params, opt_state, img, lbl, restore


def time_step(bs: int, dtype, attn: str, iters: int = 20,
              remat: bool = False) -> dict:
    step, params, opt_state, img, lbl, restore = build_step(
        bs, dtype, attn, remat)
    try:
        t_c0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state, img, lbl)
        float(loss)
        compile_s = time.perf_counter() - t_c0
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, img, lbl)
        float(loss)
        dt = time.perf_counter() - t0
        sps = bs * iters / dt
        mfu = sps * STEP_GFLOP_PER_SAMPLE / 1e3 / PEAK_TFLOPS_BF16
        return {"bs": bs, "dtype": str(dtype), "attn": attn,
                "remat": remat,
                "samples_per_s": round(sps, 1), "mfu_pct": round(100 * mfu, 1),
                "compile_s": round(compile_s, 1)}
    finally:
        restore()


def main() -> None:
    assert jax.default_backend() == "tpu", jax.default_backend()
    sizes = [int(a) for a in sys.argv[1:]] or [64]
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".tune_vit_tpu.jsonl")
    configs = [(jnp.bfloat16, "xla", False), (jnp.bfloat16, "pallas", False)]
    if not os.environ.get("RAFIKI_TUNE_BF16_ONLY"):
        # the f32 Pallas compile wedged a 51-min remote-compile RPC on
        # 2026-07-31; retry chains skip it so a flaky tunnel window is
        # spent on the configs that decide the headline number
        configs.append((None, "pallas", False))
    for bs in sizes:
        cfgs = list(configs)
        if bs == max(sizes):
            # remat at the biggest batch: where activation HBM binds,
            # rematerialization may net out faster via utilization
            cfgs.append((jnp.bfloat16, "xla", True))
        for dtype, attn, remat in cfgs:
            r = time_step(bs, dtype, attn, remat=remat)
            line = json.dumps(r)
            print(line, flush=True)
            with open(out, "a") as f:  # survive parent timeouts
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())


if __name__ == "__main__":
    main()
