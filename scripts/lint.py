#!/usr/bin/env python
"""Standalone lint runner for repo checkouts (no install needed).

Equivalent to ``rafiki-tpu lint`` / ``rafiki-tpu-lint``; defaults to
analyzing ``rafiki_tpu/`` relative to the repo root so CI can run it
as ``python scripts/lint.py`` from anywhere. The repo self-check runs
the whole-program rules too, so ``--project`` is ON by default here —
pass explicit flags to opt into a narrower run.
"""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from rafiki_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    os.chdir(_REPO_ROOT)  # "rafiki_tpu" default path resolves here
    argv = sys.argv[1:]
    if "--project" not in argv:
        argv = ["--project"] + argv
    sys.exit(main(argv))
