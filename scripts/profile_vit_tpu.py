"""Name the ViT-B/16 MFU gap (VERDICT r4 item 2): XLA cost analysis +
roofline classification + a jax.profiler trace of the train step.

The r4 sweep measured 21.7% MFU (811 samples/s, bs=64, bf16 + XLA
attention) with no committed analysis of WHERE the other ~78% goes.
This script, run in a claimable tunnel window:

1. builds the EXACT step every hardware experiment measures
   (``tune_vit_tpu.build_step`` — both the record-holding XLA-attention
   arm and the Pallas arm),
2. AOT-compiles it once (``lower().compile()``) and pulls the
   executable's own ``cost_analysis()`` — XLA's FLOP count and
   bytes-accessed estimate for the REAL optimized HLO. (Pallas-arm
   caveat recorded per row: cost_analysis undercounts custom-call
   FLOPs, so its roofline is a lower bound),
3. computes the roofline bound ``max(flops/PEAK, bytes/HBM_BW)`` per
   step and labels it compute-bound or HBM-bound,
4. times the SAME compiled executable and reports roofline efficiency
   (what's left after the binding resource — scheduling, overheads),
5. captures a ``jax.profiler.trace`` of 5 steps under
   ``.profiles/vit_{attn}_bs{N}/`` for TensorBoard/Perfetto reading.

Appends one JSON row per (attn, bs) to ``.profile_vit_tpu.jsonl`` so a
mid-window outage keeps completed rows (the chain's append-to-file
discipline). Usage: python scripts/profile_vit_tpu.py [bs ...]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from tune_vit_tpu import PEAK_TFLOPS_BF16, build_step

HBM_GBPS = 819.0  # v5e HBM bandwidth


def profile_step(bs: int, attn: str) -> dict:
    step, params, opt_state, img, lbl, restore = build_step(
        bs, jnp.bfloat16, attn)
    try:
        compiled = step.lower(params, opt_state, img, lbl).compile()
        cost = compiled.cost_analysis() or {}
        if isinstance(cost, (list, tuple)):  # older jax: per-device list
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        bytes_acc = float(cost.get("bytes accessed", 0.0))

        # run the SAME executable we analyzed (donated buffers: feed
        # each step's outputs back in)
        params, opt_state, loss = compiled(params, opt_state, img, lbl)
        float(loss)
        iters = 20
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = compiled(params, opt_state, img,
                                               lbl)
        float(loss)
        step_s = (time.perf_counter() - t0) / iters

        # roofline: the binding resource's minimum time for this step
        t_compute = flops / (PEAK_TFLOPS_BF16 * 1e12)
        t_hbm = bytes_acc / (HBM_GBPS * 1e9)
        bound = "compute" if t_compute >= t_hbm else "hbm"
        roofline_s = max(t_compute, t_hbm)

        trace_dir = os.path.abspath(f".profiles/vit_{attn}_bs{bs}")
        os.makedirs(trace_dir, exist_ok=True)
        with jax.profiler.trace(trace_dir):
            for _ in range(5):
                params, opt_state, loss = compiled(params, opt_state,
                                                   img, lbl)
            float(loss)

        return {
            "bs": bs, "attn": attn,
            "samples_per_s": round(bs / step_s, 1),
            "step_ms": round(step_s * 1e3, 2),
            "xla_flops_per_step": flops,
            "xla_bytes_per_step": bytes_acc,
            "roofline_ms": round(roofline_s * 1e3, 2),
            "t_compute_ms": round(t_compute * 1e3, 2),
            "t_hbm_ms": round(t_hbm * 1e3, 2),
            "bound": bound,
            # fraction of the BINDING resource's peak actually achieved
            # — mfu alone can't distinguish "HBM-bound and efficient"
            # from "compute-bound and stalling"
            "roofline_efficiency_pct": round(
                100 * roofline_s / step_s, 1),
            "mfu_pct": round(
                100 * flops / (step_s * PEAK_TFLOPS_BF16 * 1e12), 1),
            # Pallas custom calls are invisible to cost_analysis: the
            # pallas arm's flops/roofline are LOWER bounds
            "flops_undercounted": attn == "pallas",
            "trace_dir": trace_dir,
        }
    finally:
        restore()


def main() -> None:
    assert jax.default_backend() == "tpu", jax.default_backend()
    batches = [int(a) for a in sys.argv[1:]] or [64, 128, 256]
    for bs in batches:
        for attn in ("xla", "pallas"):
            try:
                row = profile_step(bs, attn)
            except Exception as e:  # noqa: BLE001 — e.g. OOM at 256
                row = {"bs": bs, "attn": attn, "error": repr(e)[:200]}
            with open(".profile_vit_tpu.jsonl", "a") as f:
                f.write(json.dumps(row) + "\n")
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
