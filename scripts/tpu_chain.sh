#!/bin/bash
# Claim-early retry chain for live-TPU measurements (VERDICT r3 item #1).
#
# Protocol (established rounds 2-4): claim the tunnel at session start and
# keep retrying; each attempt is its own clean-exiting process; NEVER
# SIGKILL a claimant (a killed claimant leaves a stale server-side lease
# that blocks every later claim until it expires) — overdue attempts are
# ABANDONED and the loop moves on, failing fast while the orphan holds
# the claim and succeeding once it dies.
#
# Stages per successful claim window:
#   1. scripts/tune_vit_tpu.py 128 256  (bf16-only sweep -> .tune_vit_tpu.jsonl)
#   2. bench.py                          (headline ViT-B/16 number)
#   3. bench_extra.py                    (predictor req/s + p50, advisor trials/hour)
# Stage results persist via each script's own append-to-file discipline,
# so a mid-chain tunnel outage keeps everything already measured.
set -u
cd /root/repo
LOG=${TPU_CHAIN_LOG:-.tpu_chain_s3.log}
DONEFILE=.tpu_chain_s3.done

run_capped() {  # run_capped <cap_s> <cmd...>: abandon (not kill) overdue child
  local cap=$1; shift
  "$@" >>"$LOG" 2>&1 &
  local pid=$! t=0
  while kill -0 "$pid" 2>/dev/null; do
    sleep 20; t=$((t + 20))
    if [ "$t" -ge "$cap" ]; then
      echo "--- abandoning overdue pid $pid after ${t}s (not killed)" >>"$LOG"
      return 9
    fi
  done
  wait "$pid"
}

for i in $(seq 1 60); do
  echo "=== attempt $i $(date -u +%F' '%T) ===" >>"$LOG"
  RAFIKI_TUNE_BF16_ONLY=1 run_capped 2400 python scripts/tune_vit_tpu.py 128 256
  rc=$?
  echo "--- tune rc=$rc" >>"$LOG"
  if [ "$rc" -eq 0 ]; then
    echo "=== tune OK -> bench.py ===" >>"$LOG"
    RAFIKI_BENCH_DEADLINE=420 run_capped 600 python bench.py
    echo "--- bench rc=$?" >>"$LOG"
    echo "=== -> bench_extra.py ===" >>"$LOG"
    RAFIKI_BENCH_DEADLINE=900 run_capped 1100 python bench_extra.py
    echo "--- bench_extra rc=$?" >>"$LOG"
    echo "=== chain complete $(date -u +%T) ===" >>"$LOG"
    date -u +%F' '%T >"$DONEFILE"
    exit 0
  fi
  sleep 45
done
echo "=== chain exhausted all attempts ===" >>"$LOG"
exit 1
