#!/bin/bash
# Claim-early retry chain for live-TPU measurements (VERDICT r4 item #1).
#
# Protocol (established rounds 2-4): claim the tunnel at session start and
# keep retrying; each attempt is its own clean-exiting process; NEVER
# SIGKILL a claimant (a killed claimant leaves a stale server-side lease
# that blocks every later claim until it expires) — overdue attempts are
# ABANDONED and the loop moves on, failing fast while the orphan holds
# the claim and succeeding once it dies.
#
# Round-5 ordering change (VERDICT r4 item #1): bench.py runs FIRST each
# attempt, so even a short claimable window produces a driver-format TPU
# record (backend:"tpu", bs sweep 32/128/256, kernels check) before the
# longer sweeps. A failed claim surfaces as bench.py's cpu-fallback line;
# the chain greps the emitted JSON for backend:"tpu" to detect a window.
#
# Stages per successful claim window:
#   1. bench.py                          (headline ViT-B/16 record, bs<=256)
#   2. bench_extra.py                    (predictor req/s + p50, advisor
#                                         trials/hour — first-ever on-chip)
#   3. scripts/tune_vit_tpu.py 128 256   (bf16 MFU sweep incl. remat)
#   4. scripts/tune_attention_tpu.py     (Pallas-vs-XLA crossover table)
# Stage results persist via each script's own append-to-file discipline,
# so a mid-chain tunnel outage keeps everything already measured.
set -u
cd /root/repo
LOG=${TPU_CHAIN_LOG:-.tpu_chain_r5.log}
DONEFILE=.tpu_chain_r5.done

run_capped() {  # run_capped <cap_s> <cmd...>: abandon (not kill) overdue child
  local cap=$1; shift
  "$@" >>"$LOG" 2>&1 &
  local pid=$! t=0
  while kill -0 "$pid" 2>/dev/null; do
    sleep 20; t=$((t + 20))
    if [ "$t" -ge "$cap" ]; then
      echo "--- abandoning overdue pid $pid after ${t}s (not killed)" >>"$LOG"
      return 9
    fi
  done
  wait "$pid"
}

# Startup guard: abandoned claimants from a previous chain may still be
# blocked inside the tunnel claim — launching another claimant alongside
# them invites contention. Wait (up to ~30 min) for them to drain.
for _ in $(seq 1 90); do
  pgrep -f "bench.py --child|bench_extra.py --child|tune_vit_tpu.py|tune_attention_tpu.py|profile_vit_tpu.py" >/dev/null || break
  echo "--- waiting for orphan claimants to drain $(date -u +%T)" >>"$LOG"
  sleep 20
done

for i in $(seq 1 40); do
  echo "=== attempt $i $(date -u +%F' '%T) ===" >>"$LOG"
  OUT=.tpu_bench_try.$i.json
  : >"$OUT"
  # Deadline 1500s: a failed claim blocks ~25 min server-side before
  # UNAVAILABLE, so the accel child is abandoned just before resolution
  # and at most one claimant is in flight per attempt.
  RAFIKI_BENCH_DEADLINE=1500 run_capped 1620 \
    bash -c "python bench.py >$OUT"
  rc=$?
  echo "--- bench rc=$rc emitted: $(cat "$OUT")" >>"$LOG"
  # window open = a REAL vit throughput row on tpu; bench_error also
  # carries backend:"tpu" when the probe succeeded but the sweep hung
  if grep -q '"backend": "tpu"' "$OUT" && \
     ! grep -q '"metric": "bench_error"' "$OUT"; then
    cp "$OUT" .bench_tpu_r5.json
    echo "=== TPU window OPEN -> bench_extra ===" >>"$LOG"
    RAFIKI_BENCH_DEADLINE=900 run_capped 1100 python bench_extra.py
    echo "--- bench_extra rc=$?" >>"$LOG"
    echo "=== -> tune_vit sweep ===" >>"$LOG"
    RAFIKI_TUNE_BF16_ONLY=1 run_capped 2400 \
      python scripts/tune_vit_tpu.py 128 256
    echo "--- tune_vit rc=$?" >>"$LOG"
    echo "=== -> tune_attention sweep ===" >>"$LOG"
    run_capped 2400 python scripts/tune_attention_tpu.py
    echo "--- tune_attention rc=$?" >>"$LOG"
    echo "=== -> profile (cost analysis + trace) ===" >>"$LOG"
    run_capped 1200 python scripts/profile_vit_tpu.py 64 128 256
    echo "--- profile rc=$?" >>"$LOG"
    echo "=== chain complete $(date -u +%T) ===" >>"$LOG"
    date -u +%F' '%T >"$DONEFILE"
    exit 0
  fi
  sleep 45
done
echo "=== chain exhausted all attempts ===" >>"$LOG"
exit 1
