#!/bin/sh
# Pre-commit lint gate. Install with:
#   ln -s ../../scripts/precommit.sh .git/hooks/pre-commit
#
# Per-module AND path-sensitive flow rules (lock-release-path,
# use-after-donate, ...) run only on the files you changed (vs HEAD,
# plus untracked files) so the hook stays fast on a big tree — flow
# rules live in the same per-file pass, so --changed-only scopes them
# for free. The whole-program rules always see the full package,
# because cross-layer contracts (hub verb parity, lock ordering,
# metric catalogs) can be broken by files you did NOT touch — and the
# thread-model race layer (shared-state-race, atomic-rmw-race,
# thread-lifecycle) rides in the same --project pass: a race pairs a
# spawn site in one file with a bare write in another.
set -e
cd "$(dirname "$0")/.."
exec python scripts/lint.py --changed-only HEAD --project rafiki_tpu
