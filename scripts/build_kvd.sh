#!/bin/sh
# Build the rafiki-kvd data plane binary and the BPE shared object.
#
#   scripts/build_kvd.sh                              # optimized
#   scripts/build_kvd.sh --sanitize=address           # ASan
#   scripts/build_kvd.sh --sanitize=thread            # TSan
#   scripts/build_kvd.sh --sanitize=undefined         # UBSan
#
# Sanitized artifacts get distinct names (rafiki-kvd-address,
# librbpe-address.so) so they never shadow the production binary;
# tests opt in per-process via KVServer(sanitize="address") or the
# RAFIKI_KVD_SANITIZE environment variable.
set -e
cd "$(dirname "$0")/../rafiki_tpu/native"

SANITIZE=""
for arg in "$@"; do
  case "$arg" in
    --sanitize=address|--sanitize=thread|--sanitize=undefined)
      SANITIZE="${arg#--sanitize=}" ;;
    *)
      echo "usage: $0 [--sanitize=address|thread|undefined]" >&2
      exit 2 ;;
  esac
done

if [ -n "$SANITIZE" ]; then
  exec make all "SANITIZE=$SANITIZE"
fi
exec make all
