"""Persistence: MetaStore (system metadata) and ParamStore (trial params)."""

from .meta_store import MetaStore
from .param_store import (FileBackend, InMemoryBackend, ParamStore,
                          params_from_bytes, params_to_bytes)

__all__ = ["MetaStore", "ParamStore", "FileBackend", "InMemoryBackend",
           "params_from_bytes", "params_to_bytes"]
