"""Sharded checkpointing: per-shard files, no full-tree host blob.

SURVEY.md §5.4 obligates Orbax-style sharded checkpoints for the
rebuild: ``ParamStore``'s default msgpack blob serializes the WHOLE
pytree through one host buffer — fine at tuning-trial scale, unusable
for an 8B model (a ≥16 GB blob whose assembly all-gathers every fsdp
shard to one host, defeating the sharding). This module implements the
same sharded-directory semantics natively (full control over the
format, testable shard-ownership logic — the Orbax/tensorstore layers
it replaces are driver plumbing, not TPU math):

- The manifest is computed from each leaf's GLOBAL sharding
  (``sharding.devices_indices_map``), so every process derives the
  identical manifest and identical content-addressed file names
  (``L{leaf}.S{shard}`` numbered over the sorted global bounds list) —
  hosts can never collide on names or under-describe each other's
  shards.
- ``save`` streams: one shard is copied to host, written, and released
  at a time — peak host memory is ONE SHARD. Each process writes only
  shards it owns (default: addressable && replica 0 — the disjoint-
  writer rule jax.distributed gives every host); process 0 writes the
  manifest LAST as the atomic commit marker.
- ``save_async`` must instead snapshot its owned shards to host BEFORE
  returning (training loops donate their param buffers to the next
  step), then writes on a background thread: peak host memory is this
  process's tree portion — tree/P per host in multi-host, and on a
  single host the same transient footprint the blob path pays, minus
  the msgpack double-buffer, with the file I/O overlapped.
- ``restore`` builds each leaf via ``jax.make_array_from_callback``
  over a caller-supplied sharding: each requested device shard reads
  only the overlapping saved shard files (fast path: identical
  topology → exactly one file). Restoring to a DIFFERENT mesh/sharding
  works — overlaps are assembled shard-by-shard.

Format: ``<root>/<name>/manifest.json`` + ``L{leaf:04d}.S{shard:03d}.bin``
(raw C-order bytes; bounds and dtype live in the manifest).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..utils.pytree import leaf_paths, set_path

_MANIFEST = "manifest.json"
_FORMAT = "rafiki-sharded-ckpt-v1"


def _index_to_bounds(index, shape) -> List[List[int]]:
    """Per-dim [start, stop] of a shard's slice tuple (None → full)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _global_bounds(leaf) -> List[List[List[int]]]:
    """Sorted unique shard bounds over the leaf's FULL (global)
    sharding — identical on every process, so manifests and file names
    agree across hosts."""
    shape = tuple(leaf.shape)
    idx_map = leaf.sharding.devices_indices_map(shape)
    uniq = {tuple(map(tuple, _index_to_bounds(idx, shape)))
            for idx in idx_map.values()}
    return [list(map(list, b)) for b in sorted(uniq)]


class ShardedCheckpointer:
    """Directory-per-checkpoint sharded save/restore under ``root``."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._async_lock = threading.Lock()
        self._pending: Optional[threading.Thread] = None
        self._pending_error: Optional[BaseException] = None

    # ---- paths ----
    def _dir(self, name: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in name)
        return os.path.join(self.root, safe)

    def exists(self, name: str) -> bool:
        return os.path.exists(os.path.join(self._dir(name), _MANIFEST))

    def delete(self, name: str) -> None:
        self.wait(reraise=False)  # never race an in-flight writer
        shutil.rmtree(self._dir(name), ignore_errors=True)

    # ---- save ----
    def _plan(self, tree: Any) -> Dict[str, Any]:
        """The manifest, derived from GLOBAL shardings only (no data
        touched) — deterministic and identical on every process."""
        manifest: Dict[str, Any] = {"format": _FORMAT, "leaves": []}
        for li, (path, leaf) in enumerate(leaf_paths(tree)):
            shape = tuple(getattr(leaf, "shape", ()))
            dtype = np.dtype(getattr(leaf, "dtype", np.float64)).name
            if hasattr(leaf, "sharding") and hasattr(leaf.sharding,
                                                     "devices_indices_map"):
                bounds = _global_bounds(leaf)
            else:  # host array: one full-extent shard
                bounds = [_index_to_bounds(
                    (slice(None),) * len(shape), shape)]
            manifest["leaves"].append({
                "path": list(path), "shape": list(shape), "dtype": dtype,
                "shards": [{"bounds": b,
                            "file": f"L{li:04d}.S{si:03d}.bin"}
                           for si, b in enumerate(bounds)]})
        return manifest

    def _owned_blocks(self, tree: Any, manifest: Dict[str, Any],
                      owns: Optional[Callable[[Any], bool]],
                      process_index: int
                      ) -> Iterator[Tuple[str, Any]]:
        """(file name, shard-data thunk) for every shard THIS process
        writes. Data is materialized by the caller one thunk at a time
        (sync save streams; async save snapshots the list up front)."""
        if owns is None:
            def owns(shard) -> bool:
                return shard.replica_id == 0

        for li, (path, leaf) in enumerate(leaf_paths(tree)):
            entry = manifest["leaves"][li]
            fname_by_bounds = {
                tuple(map(tuple, s["bounds"])): s["file"]
                for s in entry["shards"]}
            if hasattr(leaf, "addressable_shards"):
                emitted = set()
                for shard in leaf.addressable_shards:
                    key = tuple(map(tuple, _index_to_bounds(
                        shard.index, leaf.shape)))
                    if key in emitted or not owns(shard):
                        continue
                    emitted.add(key)
                    yield (fname_by_bounds[key],
                           lambda s=shard: np.ascontiguousarray(
                               np.asarray(s.data)))
            elif process_index == 0:
                yield (entry["shards"][0]["file"],
                       lambda x=leaf: np.ascontiguousarray(
                           np.asarray(x)))

    def _prepare_dir(self, name: str, process_index: int) -> str:
        d = self._dir(name)
        if os.path.exists(d) and process_index == 0:
            shutil.rmtree(d, ignore_errors=True)  # no stale shard files
        os.makedirs(d, exist_ok=True)
        return d

    def _commit(self, d: str, manifest: Dict[str, Any],
                process_index: int) -> None:
        if process_index == 0:
            tmp = os.path.join(d, _MANIFEST + ".tmp")
            with open(tmp, "w") as f:
                json.dump(manifest, f)
            os.replace(tmp, os.path.join(d, _MANIFEST))  # commit marker

    def save(self, name: str, tree: Any,
             owns: Optional[Callable[[Any], bool]] = None,
             process_index: Optional[int] = None,
             sync_fn: Optional[Callable[[str], None]] = None) -> int:
        """Write ``tree`` streaming (one shard on host at a time);
        returns bytes written BY THIS PROCESS.

        ``owns(shard) -> bool`` selects which device shards this process
        writes (default: addressable replica-0 shards). ``process_index``
        defaults to ``jax.process_index()``; only process 0 writes
        host-array leaves and the manifest.

        In a MULTI-PROCESS runtime the save self-fences: ``sync_fn(tag)``
        defaults to ``jax.experimental.multihost_utils.
        sync_global_devices`` (pass your own to override). Three
        barriers: (1) process 0's directory prep before other hosts'
        shard writes (prep deletes stale files), (2) all shard writes
        before the manifest commit (a reader who sees the manifest sees
        every shard), (3) the commit before ANY process returns — so a
        returned ``save`` means the checkpoint exists everywhere."""
        import jax

        if process_index is None:
            process_index = jax.process_index()
        if sync_fn is None and jax.process_count() > 1:
            from jax.experimental import multihost_utils

            sync_fn = multihost_utils.sync_global_devices
        self.wait(reraise=False)
        manifest = self._plan(tree)
        d = self._prepare_dir(name, process_index)
        if sync_fn is not None:
            sync_fn(f"sharded-ckpt-prepared-{name}")
        written = 0
        for fname, thunk in self._owned_blocks(tree, manifest, owns,
                                               process_index):
            data = thunk()  # ONE shard on host
            with open(os.path.join(d, fname), "wb") as f:
                f.write(data.tobytes())
            written += data.nbytes
        if sync_fn is not None:
            sync_fn(f"sharded-ckpt-written-{name}")
        self._commit(d, manifest, process_index)
        if sync_fn is not None:
            sync_fn(f"sharded-ckpt-committed-{name}")
        return written

    def save_async(self, name: str, tree: Any) -> None:
        """Snapshot this process's shards to host NOW (donation-safe —
        the caller's training loop will invalidate the device buffers),
        write files on a background thread (one in flight; a new save
        joins the previous). A failed async save is raised by the next
        ``wait()`` and logged by quiet waiters.

        In a MULTI-PROCESS runtime this degrades to the synchronous,
        barrier-fenced :meth:`save`: the cross-host fences must run on
        the main thread (collectives may not race the training step
        from a background thread), and an unfenced async write would
        let one host's directory prep delete another's in-flight
        shards."""
        import jax

        if jax.process_count() > 1:
            self.save(name, tree)
            return
        self.wait(reraise=False, log=True)
        process_index = jax.process_index()
        manifest = self._plan(tree)
        blocks = [(fname, thunk())  # materialize before donation
                  for fname, thunk in self._owned_blocks(
                      tree, manifest, None, process_index)]

        def run() -> None:
            try:
                d = self._prepare_dir(name, process_index)
                for fname, data in blocks:
                    with open(os.path.join(d, fname), "wb") as f:
                        f.write(data.tobytes())
                self._commit(d, manifest, process_index)
            except BaseException as e:  # noqa: BLE001 — held for wait()
                # wait() joins the thread before touching the parked
                # error, so the two writers are join-ordered — a
                # happens-before edge the static race model can't see
                self._pending_error = e  # rafiki: noqa[shared-state-race]

        with self._async_lock:
            self._pending = threading.Thread(target=run, daemon=True)
            self._pending.start()

    def wait(self, reraise: bool = True, log: bool = False) -> None:
        """Join any in-flight async save. ``reraise=False`` swallows a
        parked failure (optionally logging it) — the mode for cleanup
        and presence probes, where a stale write error from SOME EARLIER
        trial must not detonate an unrelated code path (trial fault
        isolation)."""
        with self._async_lock:
            t, self._pending = self._pending, None
        if t is not None:
            t.join()
        if self._pending_error is not None:
            e, self._pending_error = self._pending_error, None
            if reraise:
                raise e
            if log:
                import logging

                logging.getLogger(__name__).warning(
                    "async sharded checkpoint save failed", exc_info=e)

    def copy(self, src: str, dst: str) -> bool:
        """Directory-level checkpoint copy (the resume pre-seed path)."""
        self.wait(reraise=False)
        if not self.exists(src):
            return False
        self.delete(dst)
        shutil.copytree(self._dir(src), self._dir(dst))
        return True

    # ---- restore ----
    def manifest_shapes(self, name: str) -> Dict[Tuple[str, ...],
                                                 Tuple[int, ...]]:
        """leaf path → shape, from the manifest only (no data reads) —
        the cheap compatibility probe for warm-start gating."""
        with open(os.path.join(self._dir(name), _MANIFEST)) as f:
            manifest = json.load(f)
        return {tuple(e["path"]): tuple(e["shape"])
                for e in manifest["leaves"]}

    def restore(self, name: str, template: Any) -> Any:
        """Rebuild the tree into ``template``'s structure. Template
        leaves that are jax arrays with shardings restore INTO those
        shardings (per-device shard reads); plain numpy/abstract leaves
        restore as host arrays."""
        import jax

        d = self._dir(name)
        self.wait(reraise=False)
        with open(os.path.join(d, _MANIFEST)) as f:
            manifest = json.load(f)
        if manifest.get("format") != _FORMAT:
            raise ValueError(f"{d}: unknown checkpoint format")
        by_path = {tuple(e["path"]): e for e in manifest["leaves"]}

        out = jax.tree_util.tree_map(lambda x: x, template)
        for path, leaf in leaf_paths(template):
            entry = by_path.get(path)
            if entry is None:
                raise KeyError(f"checkpoint {name!r} is missing leaf "
                               f"{'/'.join(path)}")
            shape = tuple(entry["shape"])
            dtype = np.dtype(entry["dtype"])
            want = tuple(getattr(leaf, "shape", shape))
            if want != shape:
                raise ValueError(
                    f"{'/'.join(path)}: checkpoint shape {shape} != "
                    f"template shape {want}")

            def read(idx, entry=entry, shape=shape, dtype=dtype):
                # assemble the requested slice from overlapping shard
                # files; identical-topology fast path = one exact file
                starts = [0 if s.start is None else int(s.start)
                          for s in idx]
                stops = [dim if s.stop is None else int(s.stop)
                         for s, dim in zip(idx, shape)]
                out_arr = np.empty([b - a for a, b in
                                    zip(starts, stops)], dtype)
                filled = 0
                for sh in entry["shards"]:
                    b = sh["bounds"]
                    lo = [max(a, bb[0]) for a, bb in zip(starts, b)]
                    hi = [min(s, bb[1]) for s, bb in zip(stops, b)]
                    if any(l >= h for l, h in zip(lo, hi)):
                        continue
                    block = np.fromfile(
                        os.path.join(d, sh["file"]), dtype).reshape(
                        [bb[1] - bb[0] for bb in b])
                    src = tuple(slice(l - bb[0], h - bb[0])
                                for l, h, bb in zip(lo, hi, b))
                    dst = tuple(slice(l - a, h - a)
                                for l, h, a in zip(lo, hi, starts))
                    out_arr[dst] = block[src]
                    filled += int(np.prod([h - l for l, h
                                           in zip(lo, hi)]))
                if filled != out_arr.size:
                    raise ValueError(
                        f"{'/'.join(entry['path'])}: shard files cover "
                        f"{filled}/{out_arr.size} of the requested "
                        "slice (partial/corrupt checkpoint)")
                return out_arr

            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and hasattr(sharding, "mesh"):
                arr = jax.make_array_from_callback(shape, sharding, read)
            else:
                arr = read(tuple(slice(None) for _ in shape))
            set_path(out, path, arr)
        return out

    def total_bytes(self, name: str) -> int:
        """On-disk payload size (shard files, excluding the manifest)."""
        d = self._dir(name)
        return sum(os.path.getsize(os.path.join(d, f))
                   for f in os.listdir(d) if f.endswith(".bin"))


class ShardedCheckpointRef:
    """Lazy handle to a sharded checkpoint, passed where a host params
    tree would otherwise go (``TrainContext.shared_params``): the
    consumer template calls :meth:`restore` with its OWN sharded
    template, so the warm-start path never assembles the full tree on a
    host either. :meth:`matches` is the manifest-only shape probe a
    template uses to DECIDE whether to warm start (mirroring the blob
    path's ``same_tree_shapes`` guard) before committing to it."""

    def __init__(self, checkpointer: ShardedCheckpointer,
                 name: str) -> None:
        self.checkpointer = checkpointer
        self.name = name

    def restore(self, template: Any) -> Any:
        return self.checkpointer.restore(self.name, template)

    def matches(self, template: Any) -> bool:
        """True iff the checkpoint's leaf paths/shapes equal the
        template's — read from the manifest alone."""
        try:
            saved = self.checkpointer.manifest_shapes(self.name)
        except (OSError, ValueError, KeyError):
            return False
        want = {path: tuple(getattr(leaf, "shape", ()))
                for path, leaf in leaf_paths(template)}
        return saved == want

    def exists(self) -> bool:
        return self.checkpointer.exists(self.name)
