"""ParamStore: trial parameter blobs + cross-trial sharing.

Parity target: the reference's Redis-backed ParamStore with a session-level
cache (SURVEY.md §2 "Param store", §5.4): workers save a trial's parameters
after training and load them for warm starts (the paper's collaborative
tuning) and for inference-worker boot.

TPU-first deltas:
- Blobs are JAX pytrees serialized with flax's msgpack (host numpy), so
  save/load is framework-native — no pickles.
- Backends: in-process dict (tests), filesystem directory (the TPU-VM host
  plays the role the Redis container did — SURVEY.md §5.8(b)), and the
  native kv server (``rafiki_tpu.native``) for cross-host deployments.
- An LRU bytes-cache in front of any backend mirrors the reference's
  "session-level cache".
"""

from __future__ import annotations

import collections
import hashlib
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

Params = Dict[str, Any]


# ---- serialization ---------------------------------------------------------

def params_to_bytes(params: Params) -> bytes:
    from flax import serialization

    host = _to_host(params)
    return serialization.msgpack_serialize(host)


def params_from_bytes(data: bytes) -> Params:
    from flax import serialization

    return serialization.msgpack_restore(data)


def _to_host(tree: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, tree)


# ---- backends --------------------------------------------------------------

class ParamBackend:
    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def keys(self) -> List[str]:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        """Presence check without fetching the blob (override where the
        backend can do better than a full get)."""
        return self.get(key) is not None


class InMemoryBackend(ParamBackend):
    def __init__(self) -> None:
        self._data: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, key: str, data: bytes) -> None:
        with self._lock:
            self._data[key] = data

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._data)

    def exists(self, key: str) -> bool:
        with self._lock:
            return key in self._data


class FileBackend(ParamBackend):
    """One blob per file; atomic writes via rename. Keys are sanitized to
    hashes so arbitrary trial ids can't traverse paths."""

    def __init__(self, root: str) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._names: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._load_index()

    def _fname(self, key: str) -> str:
        return hashlib.sha256(key.encode()).hexdigest()[:32] + ".msgpack"

    def _load_index(self) -> None:
        idx = self.root / "index.tsv"
        if idx.exists():
            for line in idx.read_text().splitlines():
                if "\t" in line:
                    k, f = line.split("\t", 1)
                    self._names[k] = f

    def _append_index(self, key: str, fname: str) -> None:
        with open(self.root / "index.tsv", "a") as f:
            f.write(f"{key}\t{fname}\n")

    def put(self, key: str, data: bytes) -> None:
        fname = self._fname(key)
        fd, tmp = tempfile.mkstemp(dir=self.root)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, self.root / fname)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with self._lock:
            if key not in self._names:
                self._names[key] = fname
                self._append_index(key, fname)

    def get(self, key: str) -> Optional[bytes]:
        path = self.root / self._fname(key)
        try:
            return path.read_bytes()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        try:
            (self.root / self._fname(key)).unlink()
        except FileNotFoundError:
            pass
        with self._lock:
            self._names.pop(key, None)

    def keys(self) -> List[str]:
        with self._lock:
            return [k for k, f in self._names.items()
                    if (self.root / f).exists()]

    def exists(self, key: str) -> bool:
        return (self.root / self._fname(key)).exists()


class KVBackend(ParamBackend):
    """Backend over the native kv/queue data-plane server (Redis
    stand-in). The client's reconnect window means a blob save/load
    rides out a supervised kvd respawn + WAL replay instead of
    erroring the trial that issued it (every verb here — SET/GET/DEL/
    KEYS/EXISTS — replays idempotently)."""

    RETRY_WINDOW_S = 8.0

    def __init__(self, host: str = "127.0.0.1", port: int = 6399) -> None:
        from ..native.client import KVClient

        self._client = KVClient(host, port,
                                retry_window_s=self.RETRY_WINDOW_S)

    def put(self, key: str, data: bytes) -> None:
        self._client.set(f"params:{key}", data)

    def get(self, key: str) -> Optional[bytes]:
        return self._client.get(f"params:{key}")

    def delete(self, key: str) -> None:
        self._client.delete(f"params:{key}")

    def keys(self) -> List[str]:
        return [k[len("params:"):] for k in self._client.keys("params:*")]

    def exists(self, key: str) -> bool:
        return self._client.exists(f"params:{key}")


# ---- the store -------------------------------------------------------------

class ParamStore:
    """Save/load trial parameters with an LRU bytes cache."""

    def __init__(self, backend: Optional[ParamBackend] = None,
                 cache_size: int = 4) -> None:
        self.backend = backend or InMemoryBackend()
        self._cache: "collections.OrderedDict[str, bytes]" = \
            collections.OrderedDict()
        self._cache_size = cache_size
        self._lock = threading.Lock()

    @staticmethod
    def from_uri(uri: str) -> "ParamStore":
        """``mem://`` | ``file:///path`` | ``kv://host:port``."""
        if uri.startswith("mem://") or uri == "mem":
            return ParamStore(InMemoryBackend())
        if uri.startswith("file://"):
            return ParamStore(FileBackend(uri[len("file://"):]))
        if uri.startswith("kv://"):
            host, _, port = uri[len("kv://"):].partition(":")
            return ParamStore(KVBackend(host or "127.0.0.1",
                                        int(port or 6399)))
        return ParamStore(FileBackend(uri))  # bare path

    def _cache_put(self, trial_id: str, data: bytes) -> None:
        with self._lock:
            self._cache[trial_id] = data
            self._cache.move_to_end(trial_id)
            while len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)

    def save(self, trial_id: str, params: Params) -> str:
        data = params_to_bytes(params)
        self.backend.put(trial_id, data)
        self._cache_put(trial_id, data)
        return trial_id

    def load(self, trial_id: str) -> Optional[Params]:
        with self._lock:
            data = self._cache.get(trial_id)
            if data is not None:
                self._cache.move_to_end(trial_id)
        if data is None:
            data = self.backend.get(trial_id)
            if data is None:
                return None
            self._cache_put(trial_id, data)
        return params_from_bytes(data)

    def delete(self, trial_id: str) -> None:
        self.backend.delete(trial_id)
        with self._lock:
            self._cache.pop(trial_id, None)
        # a key's sharded checkpoint (if any) is the same logical object
        # — every existing cleanup path (trial completion, job sweep)
        # stays leak-free without learning a second delete call
        ckptr = self.sharded_checkpointer()
        if ckptr is not None and ckptr.exists(trial_id):
            ckptr.delete(trial_id)

    # ---- sharded checkpoints (SURVEY §5.4) ----
    def sharded_checkpointer(self):
        """The sharded (per-shard files, no full-tree blob) checkpoint
        store co-located with a file backend; None for mem/kv backends
        (callers fall back to whole-tree blobs there).

        msgpack blobs serialize the WHOLE pytree through one host buffer
        — unusable for fsdp-sharded big models; the sharded store writes
        one file per device shard instead (store/sharded_ckpt.py)."""
        if getattr(self, "_sharded", None) is None:
            if not isinstance(self.backend, FileBackend):
                return None
            from .sharded_ckpt import ShardedCheckpointer

            self._sharded = ShardedCheckpointer(
                str(self.backend.root / "sharded"))
        return self._sharded

    def save_sharded_async(self, key: str, tree: Any) -> bool:
        """Donation-safe async sharded save; False if the backend has no
        sharded store (caller should blob-save instead)."""
        ckptr = self.sharded_checkpointer()
        if ckptr is None:
            return False
        ckptr.save_async(key, tree)
        return True

    def sharded_ref(self, key: str):
        """Lazy restore handle for ``key``'s sharded checkpoint, or None
        if absent."""
        ckptr = self.sharded_checkpointer()
        if ckptr is None:
            return None
        # quiet wait: an in-flight async save must land first, but a
        # stale failure from SOME EARLIER trial's save must not detonate
        # this unrelated code path (trial fault isolation) — log only
        ckptr.wait(reraise=False, log=True)
        if not ckptr.exists(key):
            return None
        from .sharded_ckpt import ShardedCheckpointRef

        return ShardedCheckpointRef(ckptr, key)

    def copy_sharded(self, src: str, dst: str) -> bool:
        ckptr = self.sharded_checkpointer()
        if ckptr is None:
            return False
        return ckptr.copy(src, dst)  # waits internally (quiet)

    def exists_sharded(self, key: str) -> bool:
        ckptr = self.sharded_checkpointer()
        if ckptr is None:
            return False
        ckptr.wait(reraise=False, log=True)
        return ckptr.exists(key)

    def keys(self) -> List[str]:
        return self.backend.keys()

    def exists(self, trial_id: str) -> bool:
        """Presence check without fetching/decoding the blob."""
        with self._lock:
            if trial_id in self._cache:
                return True
        return self.backend.exists(trial_id)

    def copy(self, src: str, dst: str) -> bool:
        """Bytes-level blob copy — no msgpack decode/re-encode (matters
        for multi-GB checkpoints on the resume path). False if absent."""
        with self._lock:
            data = self._cache.get(src)
        if data is None:
            data = self.backend.get(src)
            if data is None:
                return False
        self.backend.put(dst, data)
        self._cache_put(dst, data)
        return True
