"""Database adapters: the MetaStore's SQL dialect seam.

SURVEY.md §7 step 3 planned "SQLite first (swap to PostgreSQL...)";
this module is that swap point (VERDICT r3 missing #6). The MetaStore
writes ONE dialect of SQL — qmark (``?``) placeholders, SQLite-flavored
DDL — and an adapter owns everything engine-specific: connections,
placeholder style, DDL translation, duplicate-column detection for
migrations, and row→dict conversion.

``SqliteAdapter`` is the embedded default (single-host control plane on
the TPU-VM — SURVEY §5.8(b)). ``PostgresAdapter`` carries the server-DB
deployment: it translates placeholders/DDL and drives psycopg2, which
is NOT in this image — constructing it without psycopg2 raises with
install instructions, and its pure-string translation logic is unit
tested without a server. New engines = one subclass.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence


class Cursor:
    """Uniform cursor result: mapping rows + rowcount."""

    def __init__(self, rows: Optional[List[Dict[str, Any]]],
                 rowcount: int) -> None:
        self._rows = rows or []
        self.rowcount = rowcount

    def fetchone(self) -> Optional[Dict[str, Any]]:
        return self._rows[0] if self._rows else None

    def fetchall(self) -> List[Dict[str, Any]]:
        return list(self._rows)


class DatabaseAdapter:
    """Engine-specific half of the MetaStore. The MetaStore calls only
    these methods plus ``execute``; SQL it passes is qmark-style with
    SQLite-flavored DDL, which each adapter translates as needed."""

    def connect(self):  # pragma: no cover - interface
        raise NotImplementedError

    def execute(self, conn, sql: str, args: Sequence[Any] = (),
                max_rows: Optional[int] = None) -> Cursor:
        """Run one statement; ``max_rows`` bounds how many result rows
        are materialized (None = all)."""
        raise NotImplementedError

    def commit(self, conn) -> None:
        conn.commit()

    def rollback(self, conn) -> None:
        """Discard the open transaction after a FAILED statement —
        without it the error leaks into the next caller's commit (and on
        engines with strict transactions, poisons the connection)."""
        try:
            conn.rollback()
        except Exception:  # rafiki: noqa[silent-except] — a dead
            pass           # connection can't rollback; the next
            # execute reports it

    def close(self, conn) -> None:
        conn.close()

    def init_schema(self, conn, schema_sql: str) -> None:
        """Create tables (idempotent) + engine session setup."""
        raise NotImplementedError

    def try_migration(self, conn, ddl: str) -> bool:
        """Run an ``ALTER TABLE ... ADD COLUMN``; False when the column
        already exists (the no-op re-run), raise on anything else."""
        raise NotImplementedError

    def backup(self, conn, path: str) -> None:
        """Consistent online snapshot of the whole database to a file
        at ``path``. Engines without a one-file snapshot concept may
        raise NotImplementedError."""
        raise NotImplementedError(
            "online backup is not supported by this database engine")


# ---------------------------------------------------------------- sqlite

class SqliteAdapter(DatabaseAdapter):
    def __init__(self, path: str, read_only: bool = False) -> None:
        self.path = path
        #: open via the ro URI: auditors (doctor) must not be able to
        #: write — or migrate — a live stack's database, and sqlite
        #: refuses to CREATE a missing file in this mode
        self.read_only = read_only and path != ":memory:"

    def connect(self):
        import sqlite3

        if self.read_only:
            conn = sqlite3.connect(f"file:{self.path}?mode=ro",
                                   uri=True, check_same_thread=False)
            conn.execute("PRAGMA busy_timeout=10000")
        else:
            conn = sqlite3.connect(self.path, check_same_thread=False)
        conn.row_factory = sqlite3.Row
        return conn

    def execute(self, conn, sql: str, args: Sequence[Any] = (),
                max_rows: Optional[int] = None) -> Cursor:
        cur = conn.execute(sql, tuple(args))
        if cur.description is None:
            rows = None
        elif max_rows is not None:
            rows = [dict(r) for r in cur.fetchmany(max_rows)]
        else:
            rows = [dict(r) for r in cur.fetchall()]
        return Cursor(rows, cur.rowcount)

    def init_schema(self, conn, schema_sql: str) -> None:
        if self.path != ":memory:":
            conn.execute("PRAGMA journal_mode=WAL")
        # cross-process writers: wait instead of instant 'database is
        # locked' (the MetaStore lock only serializes one process)
        conn.execute("PRAGMA busy_timeout=10000")
        conn.execute("PRAGMA foreign_keys=ON")
        conn.executescript(schema_sql)

    def backup(self, conn, path: str) -> None:
        """SQLite online backup API: page-wise copy that is consistent
        under concurrent writers (WAL readers keep going). Falls back
        to ``VACUUM INTO`` (sqlite >= 3.27) when the driver lacks
        ``Connection.backup``. The destination is replaced atomically
        via a temp file so a crash mid-backup never leaves a torn
        snapshot at ``path``."""
        import os
        import sqlite3

        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            if hasattr(conn, "backup"):
                dest = sqlite3.connect(tmp)
                try:
                    conn.backup(dest)
                finally:
                    dest.close()
            else:  # pragma: no cover - ancient driver fallback
                conn.execute("VACUUM INTO ?", (tmp,))
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def try_migration(self, conn, ddl: str) -> bool:
        import sqlite3

        try:
            conn.execute(ddl)
            return True
        except sqlite3.OperationalError as e:
            if "duplicate column" in str(e).lower():
                return False  # already migrated — the no-op re-run
            raise  # locked DB / real DDL failure must not be silent:
            # running without the column breaks every later write


# -------------------------------------------------------------- postgres

def qmark_to_format(sql: str) -> str:
    """``?`` placeholders → ``%s`` (psycopg2 paramstyle), leaving quoted
    literals untouched."""
    out: List[str] = []
    in_str = False
    for ch in sql:
        if ch == "'":
            in_str = not in_str
            out.append(ch)
        elif ch == "?" and not in_str:
            out.append("%s")
        else:
            out.append(ch)
    return "".join(out)


def sqlite_ddl_to_postgres(schema_sql: str) -> str:
    """SQLite-flavored DDL → PostgreSQL: AUTOINCREMENT ids become
    BIGSERIAL, BLOB becomes BYTEA, REAL becomes DOUBLE PRECISION."""
    sql = re.sub(r"INTEGER PRIMARY KEY AUTOINCREMENT",
                 "BIGSERIAL PRIMARY KEY", schema_sql)
    sql = re.sub(r"\bBLOB\b", "BYTEA", sql)
    sql = re.sub(r"\bREAL\b", "DOUBLE PRECISION", sql)
    return sql


# backup() is intentionally unimplemented: Postgres has no one-file
# snapshot, and the DatabaseAdapter contract says such engines raise
# NotImplementedError (callers feature-test via try/except)
class PostgresAdapter(DatabaseAdapter):  # rafiki: noqa[hub-verb-parity]
    """MetaStore on a PostgreSQL server (multi-host control planes).

    Translation is pure string work (unit-tested without a server); the
    driver is psycopg2, imported lazily so the sqlite-only image never
    needs it."""

    def __init__(self, url: str) -> None:
        try:
            import psycopg2  # noqa: F401
            import psycopg2.extras  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "PostgresAdapter needs psycopg2 (pip install "
                "psycopg2-binary); this image ships sqlite-only — use a "
                "path/sqlite:// url, or install the driver on the "
                "control-plane host") from e
        self.url = url

    def connect(self):
        import psycopg2
        import psycopg2.extras

        conn = psycopg2.connect(
            self.url, cursor_factory=psycopg2.extras.RealDictCursor)
        # autocommit: every MetaStore write is a single fenced statement
        # (atomic on its own), reads must not pin an idle-in-transaction
        # snapshot, and a failed statement must not abort a shared
        # transaction that poisons every later call on this connection
        conn.autocommit = True
        return conn

    def execute(self, conn, sql: str, args: Sequence[Any] = (),
                max_rows: Optional[int] = None) -> Cursor:
        with conn.cursor() as cur:
            cur.execute(qmark_to_format(sql), tuple(args))
            if cur.description is None:
                rows = None
            elif max_rows is not None:
                rows = [dict(r) for r in cur.fetchmany(max_rows)]
            else:
                rows = [dict(r) for r in cur.fetchall()]
            return Cursor(rows, cur.rowcount)

    def commit(self, conn) -> None:
        pass  # autocommit — see connect()

    def rollback(self, conn) -> None:
        pass  # autocommit: failed statements leave no open transaction

    def init_schema(self, conn, schema_sql: str) -> None:
        with conn.cursor() as cur:
            cur.execute(sqlite_ddl_to_postgres(schema_sql))

    def try_migration(self, conn, ddl: str) -> bool:
        import psycopg2

        try:
            with conn.cursor() as cur:
                cur.execute(sqlite_ddl_to_postgres(ddl))
            return True
        except psycopg2.errors.DuplicateColumn:
            return False


def adapter_for(url_or_path: str,
                read_only: bool = False) -> DatabaseAdapter:
    """``:memory:`` / a filesystem path / ``sqlite:///path`` → SQLite;
    ``postgresql://...`` (or ``postgres://``) → PostgreSQL.
    ``read_only`` is sqlite-only (the doctor/backup CLIs audit a local
    stack's file) — asking for it on another engine is a caller bug."""
    u = str(url_or_path)
    if u.startswith(("postgresql://", "postgres://")):
        if read_only:
            raise ValueError(
                "read_only MetaStore access is only supported on the "
                "sqlite backend")
        return PostgresAdapter(u)
    if u.startswith("sqlite:///"):
        return SqliteAdapter(u[len("sqlite:///"):] or ":memory:",
                             read_only=read_only)
    return SqliteAdapter(u, read_only=read_only)
