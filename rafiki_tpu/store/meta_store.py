"""MetaStore: persistent system metadata on SQLite.

Parity target: the reference's SQLAlchemy→PostgreSQL metadata layer
(SURVEY.md §2 "MetaStore"): users, models, datasets, train jobs,
sub-train-jobs, trials, inference jobs, services, plus per-trial logs.
SQLite (WAL) replaces PostgreSQL — the control plane lives on the TPU-VM
host (SURVEY.md §5.8(b)), where an embedded DB with a single writer-lock
is the right scale; the API is backend-agnostic so a server DB can slot in.

Rows are returned as plain dicts (JSON-ready) instead of ORM objects.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS users (
    id TEXT PRIMARY KEY, email TEXT UNIQUE NOT NULL,
    password_hash TEXT NOT NULL, salt TEXT NOT NULL,
    user_type TEXT NOT NULL, banned INTEGER DEFAULT 0,
    created_at REAL NOT NULL);
CREATE TABLE IF NOT EXISTS models (
    id TEXT PRIMARY KEY, user_id TEXT NOT NULL, name TEXT NOT NULL,
    task TEXT NOT NULL, model_class TEXT NOT NULL,
    model_bytes BLOB NOT NULL, checkpoint_id TEXT,
    dependencies TEXT, access_right TEXT NOT NULL DEFAULT 'PRIVATE',
    docker_image TEXT, created_at REAL NOT NULL,
    UNIQUE(user_id, name));
CREATE TABLE IF NOT EXISTS datasets (
    id TEXT PRIMARY KEY, user_id TEXT NOT NULL, name TEXT NOT NULL,
    task TEXT NOT NULL, uri TEXT NOT NULL, size_bytes INTEGER,
    stat TEXT, created_at REAL NOT NULL);
CREATE TABLE IF NOT EXISTS train_jobs (
    id TEXT PRIMARY KEY, user_id TEXT NOT NULL, app TEXT NOT NULL,
    app_version INTEGER NOT NULL, task TEXT NOT NULL,
    budget TEXT NOT NULL, train_dataset_id TEXT NOT NULL,
    val_dataset_id TEXT NOT NULL, train_args TEXT,
    status TEXT NOT NULL, created_at REAL NOT NULL,
    stopped_at REAL, UNIQUE(user_id, app, app_version));
CREATE TABLE IF NOT EXISTS sub_train_jobs (
    id TEXT PRIMARY KEY, train_job_id TEXT NOT NULL,
    model_id TEXT NOT NULL, status TEXT NOT NULL,
    advisor_service_id TEXT, created_at REAL NOT NULL);
CREATE TABLE IF NOT EXISTS trials (
    id TEXT PRIMARY KEY, sub_train_job_id TEXT NOT NULL,
    trial_no INTEGER NOT NULL, model_id TEXT NOT NULL,
    worker_id TEXT, knobs TEXT, score REAL, budget_scale REAL DEFAULT 1.0,
    shape_signature TEXT, status TEXT NOT NULL,
    params_saved INTEGER DEFAULT 0, error TEXT, error_class TEXT,
    heartbeat_at REAL,
    started_at REAL, stopped_at REAL, created_at REAL NOT NULL);
CREATE INDEX IF NOT EXISTS idx_trials_job ON trials(sub_train_job_id);
CREATE TABLE IF NOT EXISTS trial_logs (
    id INTEGER PRIMARY KEY AUTOINCREMENT, trial_id TEXT NOT NULL,
    time REAL NOT NULL, kind TEXT NOT NULL, data TEXT NOT NULL);
CREATE INDEX IF NOT EXISTS idx_trial_logs ON trial_logs(trial_id);
CREATE TABLE IF NOT EXISTS inference_jobs (
    id TEXT PRIMARY KEY, user_id TEXT NOT NULL,
    train_job_id TEXT NOT NULL, budget TEXT, status TEXT NOT NULL,
    predictor_host TEXT, created_at REAL NOT NULL, stopped_at REAL);
CREATE TABLE IF NOT EXISTS services (
    id TEXT PRIMARY KEY, service_type TEXT NOT NULL,
    status TEXT NOT NULL, train_job_id TEXT, sub_train_job_id TEXT,
    inference_job_id TEXT, host TEXT, port INTEGER, pid INTEGER,
    devices TEXT, error TEXT, created_at REAL NOT NULL, stopped_at REAL,
    spawn_spec TEXT, start_time REAL DEFAULT 0);
CREATE TABLE IF NOT EXISTS respawn_budgets (
    lineage TEXT PRIMARY KEY, count INTEGER NOT NULL DEFAULT 0,
    updated_at REAL NOT NULL);
CREATE TABLE IF NOT EXISTS admin_lease (
    id INTEGER PRIMARY KEY CHECK (id = 1), holder TEXT NOT NULL,
    generation INTEGER NOT NULL, heartbeat_at REAL NOT NULL,
    acquired_at REAL NOT NULL, ttl_s REAL NOT NULL DEFAULT 15);
"""


def _now() -> float:
    return time.time()


def _uid() -> str:
    return uuid.uuid4().hex


class MetaStore:
    """Thread-safe CRUD over the system schema.

    The SQL here is one dialect (qmark placeholders, SQLite-flavored
    DDL); everything engine-specific lives behind a
    :class:`rafiki_tpu.store.db.DatabaseAdapter` — the swap point SURVEY
    §7 planned ("SQLite first, swap to PostgreSQL"). ``db_path`` takes a
    filesystem path / ``:memory:`` (embedded SQLite, the single-host
    default — WAL mode keeps readers unblocked during writes) or a
    ``postgresql://`` url for a server-DB control plane. One connection
    per instance with a process-wide write lock.
    """

    def __init__(self, db_path: str = ":memory:",
                 read_only: bool = False) -> None:
        from .db import adapter_for

        self._db_path = db_path
        self._read_only = read_only
        self._adapter = adapter_for(db_path, read_only=read_only)
        self._conn = self._adapter.connect()
        self._lock = threading.RLock()
        if read_only:
            # auditors (doctor --workdir, backup CLI) must not write —
            # or schema-migrate — a live stack's database: skip DDL
            # entirely; the connection itself refuses writes
            return
        with self._lock:
            self._adapter.init_schema(self._conn, _SCHEMA)
            # migrate pre-heartbeat databases (column added for
            # preemption-safe trials; no-op once present)
            self._adapter.try_migration(
                self._conn, "ALTER TABLE trials ADD COLUMN heartbeat_at "
                            "REAL")
            if self._adapter.try_migration(
                    self._conn,
                    "ALTER TABLE trials ADD COLUMN error_class TEXT"):
                # column freshly added → pre-upgrade DB. Under the old
                # semantics EVERY ERRORED row was resumable; backfill as
                # preemption-class so recorded device losses keep their
                # remaining budget instead of becoming unclaimable NULLs
                self._exec(
                    "UPDATE trials SET error_class='preemption' "
                    "WHERE status='ERRORED' AND error_class IS NULL")
            # crash-only control plane (PR 9): the service row is the
            # durable source of truth for spawn state — migrate
            # pre-recovery databases
            self._adapter.try_migration(
                self._conn, "ALTER TABLE services ADD COLUMN "
                            "spawn_spec TEXT")
            self._adapter.try_migration(
                self._conn, "ALTER TABLE services ADD COLUMN "
                            "start_time REAL DEFAULT 0")
            self._adapter.commit(self._conn)

    def close(self) -> None:
        with self._lock:
            self._adapter.close(self._conn)

    # ---- low-level helpers ----
    def _exec(self, sql: str, args: tuple = (), max_rows=None):
        """Adapter-dispatched execute (caller holds the lock or is in
        __init__); returns a uniform mapping-row cursor. A failed
        statement rolls back so the error cannot leak into the next
        caller's commit (or poison strict-transaction engines)."""
        try:
            return self._adapter.execute(self._conn, sql, args,
                                         max_rows=max_rows)
        except Exception:
            self._adapter.rollback(self._conn)
            raise

    def _insert(self, table: str, row: Dict[str, Any]) -> None:
        cols = ", ".join(row)
        ph = ", ".join("?" for _ in row)
        with self._lock:
            self._exec(f"INSERT INTO {table} ({cols}) VALUES ({ph})",
                       tuple(row.values()))
            self._adapter.commit(self._conn)

    def _update(self, table: str, row_id: str, fields: Dict[str, Any]) -> None:
        sets = ", ".join(f"{k}=?" for k in fields)
        with self._lock:
            cur = self._exec(f"UPDATE {table} SET {sets} WHERE id=?",
                             (*fields.values(), row_id))
            if cur.rowcount == 0:
                # nothing matched: discard rather than commit, so the
                # KeyError contract implies nothing was written
                self._adapter.rollback(self._conn)
                raise KeyError(f"no {table} row {row_id!r}")
            self._adapter.commit(self._conn)

    #: columns stored as JSON text, decoded on every read
    _JSON_COLS = ("knobs", "budget", "train_args", "config", "spawn_spec")

    def _decode(self, row: Dict[str, Any]) -> Dict[str, Any]:
        for col in self._JSON_COLS:
            v = row.get(col)
            if isinstance(v, str):
                try:
                    row[col] = json.loads(v)
                except ValueError:
                    pass
        return row

    def _one(self, sql: str, args: tuple = ()) -> Optional[Dict[str, Any]]:
        with self._lock:
            row = self._exec(sql, args, max_rows=1).fetchone()
        return self._decode(dict(row)) if row else None

    def _all(self, sql: str, args: tuple = ()) -> List[Dict[str, Any]]:
        with self._lock:
            rows = self._exec(sql, args).fetchall()
        return [self._decode(dict(r)) for r in rows]

    # ---- users ----
    def create_user(self, email: str, password: str,
                    user_type: str) -> Dict[str, Any]:
        salt = os.urandom(16).hex()
        row = {"id": _uid(), "email": email,
               "password_hash": _hash_password(password, salt), "salt": salt,
               "user_type": user_type, "created_at": _now()}
        self._insert("users", row)
        return self.get_user(row["id"])  # type: ignore[return-value]

    def get_user(self, user_id: str) -> Optional[Dict[str, Any]]:
        return self._one("SELECT * FROM users WHERE id=?", (user_id,))

    def get_user_by_email(self, email: str) -> Optional[Dict[str, Any]]:
        return self._one("SELECT * FROM users WHERE email=?", (email,))

    def authenticate_user(self, email: str,
                          password: str) -> Optional[Dict[str, Any]]:
        user = self.get_user_by_email(email)
        if user is None or user["banned"]:
            return None
        expected = _hash_password(password, user["salt"])
        if not hmac.compare_digest(expected, user["password_hash"]):
            return None
        return user

    def ban_user(self, user_id: str) -> None:
        self._update("users", user_id, {"banned": 1})

    # ---- models ----
    def create_model(self, user_id: str, name: str, task: str,
                     model_class: str, model_bytes: bytes,
                     dependencies: Optional[Dict[str, str]] = None,
                     access_right: str = "PRIVATE") -> Dict[str, Any]:
        row = {"id": _uid(), "user_id": user_id, "name": name, "task": task,
               "model_class": model_class, "model_bytes": model_bytes,
               "dependencies": json.dumps(dependencies or {}),
               "access_right": access_right, "created_at": _now()}
        self._insert("models", row)
        return self.get_model(row["id"])  # type: ignore[return-value]

    def get_model(self, model_id: str) -> Optional[Dict[str, Any]]:
        return self._one("SELECT * FROM models WHERE id=?", (model_id,))

    def get_model_by_name(self, user_id: str,
                          name: str) -> Optional[Dict[str, Any]]:
        return self._one(
            "SELECT * FROM models WHERE user_id=? AND name=?",
            (user_id, name))

    def get_available_models(self, task: Optional[str] = None,
                             user_id: Optional[str] = None
                             ) -> List[Dict[str, Any]]:
        """Models usable by ``user_id``: their own plus PUBLIC ones."""
        sql = "SELECT * FROM models WHERE 1=1"
        args: list = []
        if task is not None:
            sql += " AND task=?"
            args.append(task)
        if user_id is not None:
            sql += " AND (user_id=? OR access_right='PUBLIC')"
            args.append(user_id)
        return self._all(sql + " ORDER BY created_at", tuple(args))

    # ---- datasets ----
    def create_dataset(self, user_id: str, name: str, task: str, uri: str,
                       size_bytes: int = 0,
                       stat: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
        row = {"id": _uid(), "user_id": user_id, "name": name, "task": task,
               "uri": uri, "size_bytes": size_bytes,
               "stat": json.dumps(stat or {}), "created_at": _now()}
        self._insert("datasets", row)
        return self.get_dataset(row["id"])  # type: ignore[return-value]

    def get_dataset(self, dataset_id: str) -> Optional[Dict[str, Any]]:
        return self._one("SELECT * FROM datasets WHERE id=?", (dataset_id,))

    def get_datasets(self, user_id: str,
                     task: Optional[str] = None) -> List[Dict[str, Any]]:
        if task:
            return self._all(
                "SELECT * FROM datasets WHERE user_id=? AND task=?",
                (user_id, task))
        return self._all("SELECT * FROM datasets WHERE user_id=?", (user_id,))

    # ---- train jobs ----
    def create_train_job(self, user_id: str, app: str, app_version: int,
                         task: str, budget: Dict[str, Any],
                         train_dataset_id: str, val_dataset_id: str,
                         train_args: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
        row = {"id": _uid(), "user_id": user_id, "app": app,
               "app_version": app_version, "task": task,
               "budget": json.dumps(budget),
               "train_dataset_id": train_dataset_id,
               "val_dataset_id": val_dataset_id,
               "train_args": json.dumps(train_args or {}),
               "status": "STARTED", "created_at": _now()}
        self._insert("train_jobs", row)
        return self.get_train_job(row["id"])  # type: ignore[return-value]

    def get_train_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        return self._one("SELECT * FROM train_jobs WHERE id=?", (job_id,))

    def get_train_jobs_of_user(self,
                               user_id: str) -> List[Dict[str, Any]]:
        return self._all(
            "SELECT * FROM train_jobs WHERE user_id=? "
            "ORDER BY created_at DESC", (user_id,))

    def get_train_jobs_of_app(self, user_id: str,
                              app: str) -> List[Dict[str, Any]]:
        return self._all(
            "SELECT * FROM train_jobs WHERE user_id=? AND app=? "
            "ORDER BY app_version DESC", (user_id, app))

    def get_latest_train_job_of_app(self, user_id: str,
                                    app: str) -> Optional[Dict[str, Any]]:
        jobs = self.get_train_jobs_of_app(user_id, app)
        return jobs[0] if jobs else None

    def update_train_job(self, job_id: str, **fields: Any) -> None:
        self._update("train_jobs", job_id, fields)

    # ---- sub train jobs ----
    def create_sub_train_job(self, train_job_id: str,
                             model_id: str) -> Dict[str, Any]:
        row = {"id": _uid(), "train_job_id": train_job_id,
               "model_id": model_id, "status": "STARTED",
               "created_at": _now()}
        self._insert("sub_train_jobs", row)
        return self._one("SELECT * FROM sub_train_jobs WHERE id=?",
                         (row["id"],))  # type: ignore[return-value]

    def get_sub_train_job(self, sid: str) -> Optional[Dict[str, Any]]:
        return self._one("SELECT * FROM sub_train_jobs WHERE id=?", (sid,))

    def get_sub_train_jobs_of_train_job(
            self, train_job_id: str) -> List[Dict[str, Any]]:
        return self._all(
            "SELECT * FROM sub_train_jobs WHERE train_job_id=?",
            (train_job_id,))

    def update_sub_train_job(self, sid: str, **fields: Any) -> None:
        self._update("sub_train_jobs", sid, fields)

    # ---- trials ----
    def create_trial(self, sub_train_job_id: str, trial_no: int,
                     model_id: str, knobs: Dict[str, Any],
                     worker_id: str = "", budget_scale: float = 1.0,
                     shape_sig: str = "") -> Dict[str, Any]:
        row = {"id": _uid(), "sub_train_job_id": sub_train_job_id,
               "trial_no": trial_no, "model_id": model_id,
               "worker_id": worker_id, "knobs": json.dumps(knobs),
               "budget_scale": budget_scale, "shape_signature": shape_sig,
               "status": "RUNNING", "started_at": _now(),
               "created_at": _now()}
        self._insert("trials", row)
        return self.get_trial(row["id"])  # type: ignore[return-value]

    def get_trial(self, trial_id: str) -> Optional[Dict[str, Any]]:
        return self._one("SELECT * FROM trials WHERE id=?", (trial_id,))

    def update_trial(self, trial_id: str, **fields: Any) -> None:
        if "knobs" in fields and not isinstance(fields["knobs"], str):
            fields["knobs"] = json.dumps(fields["knobs"])
        self._update("trials", trial_id, fields)

    def mark_trial_completed(self, trial_id: str, score: float,
                             params_saved: bool) -> bool:
        """Fenced terminal update: only a still-RUNNING row completes.
        Returns False when a resume claimant already TERMINATED the row
        (this worker was presumed dead, e.g. a long VM suspend) — the
        caller must then NOT feed the score back to the advisor, or one
        trial_no gets double feedback."""
        with self._lock:
            cur = self._exec(
                "UPDATE trials SET status='COMPLETED', score=?, "
                "params_saved=?, stopped_at=? WHERE id=? "
                "AND status='RUNNING'",
                (score, int(params_saved), _now(), trial_id))
            self._adapter.commit(self._conn)
            return cur.rowcount == 1

    def mark_trial_errored(self, trial_id: str, error: str,
                           error_class: str = "deterministic") -> bool:
        """Fenced like :meth:`mark_trial_completed`.

        ``error_class`` records WHY the trial died, which decides whether
        peers may resume it: ``"preemption"`` (infra fault — device loss,
        OOM-kill, connection reset; worth re-running elsewhere) vs
        ``"deterministic"`` (code/knob bug recorded by a live worker —
        re-running it anywhere yields the same crash, so resume is
        forbidden and only the advisor's trial_errored accounting runs).
        """
        with self._lock:
            cur = self._exec(
                "UPDATE trials SET status='ERRORED', error=?, "
                "error_class=?, stopped_at=? "
                "WHERE id=? AND status='RUNNING'",
                (error[:4000], error_class, _now(), trial_id))
            self._adapter.commit(self._conn)
            return cur.rowcount == 1

    def heartbeat_trial(self, trial_id: str) -> None:
        """Liveness beacon: the owning worker stamps this every few
        seconds while training, so peers can tell a preempted trial from
        one that is merely slow."""
        self.update_trial(trial_id, heartbeat_at=_now())

    def claim_trial_for_resume(self, trial_id: str, worker_id: str,
                               stale_after_s: float = 60.0) -> bool:
        """Atomically take ownership of an orphaned trial for resume.

        Eligible: status ERRORED with ``error_class='preemption'`` (an
        infra fault a live worker managed to record — device loss, OOM —
        worth re-running on healthy hardware), or RUNNING with no
        heartbeat for ``stale_after_s`` — a live peer heartbeats every
        few seconds, so a fresh heartbeat means the trial is NOT orphaned
        and the claim loses. Deterministic ERRORED rows (code/knob bugs)
        are NEVER claimable: re-running them anywhere reproduces the
        crash, and N workers would otherwise re-run one bad trial up to
        N*max_resumes times (ADVICE r3). The staleness condition sits
        inside the UPDATE itself, so exactly one concurrent claimant can
        win and a revived heartbeat between scan and claim voids the
        claim. The original error text is preserved (pointer appended).
        """
        cutoff = _now() - stale_after_s
        marker = f"resumed by {worker_id}"
        with self._lock:
            cur = self._exec(
                "UPDATE trials SET status='TERMINATED', stopped_at=?, "
                "error=(CASE WHEN error IS NULL OR error='' THEN ? "
                "ELSE error || ? END) "
                "WHERE id=? AND ((status='ERRORED' AND "
                "error_class='preemption') OR (status='RUNNING' "
                "AND COALESCE(heartbeat_at, started_at, 0) < ?))",
                (_now(), marker, f" | {marker}", trial_id, cutoff))
            self._adapter.commit(self._conn)
            return cur.rowcount == 1

    def get_trials_of_sub_train_job(
            self, sub_train_job_id: str) -> List[Dict[str, Any]]:
        return self._all(
            "SELECT * FROM trials WHERE sub_train_job_id=? ORDER BY trial_no",
            (sub_train_job_id,))

    def get_trials_of_train_job(self,
                                train_job_id: str) -> List[Dict[str, Any]]:
        return self._all(
            "SELECT t.* FROM trials t JOIN sub_train_jobs s "
            "ON t.sub_train_job_id = s.id WHERE s.train_job_id=? "
            "ORDER BY t.trial_no", (train_job_id,))

    def get_best_trials_of_train_job(self, train_job_id: str,
                                     max_count: int = 2
                                     ) -> List[Dict[str, Any]]:
        """Top completed full-budget trials with saved params — the set the
        inference job deploys (reference default: top 2)."""
        return self._all(
            "SELECT t.* FROM trials t JOIN sub_train_jobs s "
            "ON t.sub_train_job_id = s.id "
            "WHERE s.train_job_id=? AND t.status='COMPLETED' "
            "AND t.params_saved=1 AND t.budget_scale>=1.0 "
            "ORDER BY t.score DESC LIMIT ?", (train_job_id, max_count))

    # ---- trial logs ----
    def add_trial_log(self, trial_id: str, kind: str, data: Dict[str, Any],
                      t: Optional[float] = None) -> None:
        with self._lock:
            self._exec(
                "INSERT INTO trial_logs (trial_id, time, kind, data) "
                "VALUES (?,?,?,?)",
                (trial_id, t if t is not None else _now(), kind,
                 json.dumps(data)))
            self._adapter.commit(self._conn)

    def get_trial_logs(self, trial_id: str) -> List[Dict[str, Any]]:
        rows = self._all(
            "SELECT * FROM trial_logs WHERE trial_id=? ORDER BY id",
            (trial_id,))
        for r in rows:
            r["data"] = json.loads(r["data"])
        return rows

    # ---- inference jobs ----
    def create_inference_job(self, user_id: str, train_job_id: str,
                             budget: Optional[Dict[str, Any]] = None
                             ) -> Dict[str, Any]:
        row = {"id": _uid(), "user_id": user_id,
               "train_job_id": train_job_id,
               "budget": json.dumps(budget or {}), "status": "STARTED",
               "created_at": _now()}
        self._insert("inference_jobs", row)
        return self.get_inference_job(row["id"])  # type: ignore[return-value]

    def get_inference_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        return self._one("SELECT * FROM inference_jobs WHERE id=?", (job_id,))

    def get_inference_jobs_of_train_job(
            self, train_job_id: str) -> List[Dict[str, Any]]:
        return self._all(
            "SELECT * FROM inference_jobs WHERE train_job_id=? "
            "ORDER BY created_at DESC", (train_job_id,))

    def get_inference_jobs(self, user_id: Optional[str] = None
                           ) -> List[Dict[str, Any]]:
        if user_id:
            return self._all(
                "SELECT * FROM inference_jobs WHERE user_id=? "
                "ORDER BY created_at DESC", (user_id,))
        return self._all(
            "SELECT * FROM inference_jobs ORDER BY created_at DESC")

    def update_inference_job(self, job_id: str, **fields: Any) -> None:
        self._update("inference_jobs", job_id, fields)

    # ---- services ----
    def create_service(self, service_type: str,
                       train_job_id: Optional[str] = None,
                       sub_train_job_id: Optional[str] = None,
                       inference_job_id: Optional[str] = None,
                       host: str = "", port: int = 0, pid: int = 0,
                       devices: Optional[List[int]] = None,
                       spawn_spec: Optional[Dict[str, Any]] = None,
                       start_time: float = 0.0) -> Dict[str, Any]:
        """``spawn_spec`` (full module/config/slot recipe) and
        ``start_time`` (kernel start time of the pid, the recycle-proof
        half of its identity) make the ROW, not the spawning admin's
        memory, the durable source of truth: a restarted admin rebuilds
        its entire process table from these columns."""
        row = {"id": _uid(), "service_type": service_type,
               "status": "STARTED", "train_job_id": train_job_id,
               "sub_train_job_id": sub_train_job_id,
               "inference_job_id": inference_job_id, "host": host,
               "port": port, "pid": pid,
               "devices": json.dumps(devices or []),
               "spawn_spec": json.dumps(spawn_spec)
               if spawn_spec is not None else None,
               "start_time": start_time, "created_at": _now()}
        self._insert("services", row)
        return self.get_service(row["id"])  # type: ignore[return-value]

    def get_service(self, service_id: str) -> Optional[Dict[str, Any]]:
        return self._one("SELECT * FROM services WHERE id=?", (service_id,))

    def get_services(self, status: Optional[str] = None
                     ) -> List[Dict[str, Any]]:
        if status:
            return self._all("SELECT * FROM services WHERE status=?",
                             (status,))
        return self._all("SELECT * FROM services")

    def update_service(self, service_id: str, **fields: Any) -> None:
        if "spawn_spec" in fields and \
                not isinstance(fields["spawn_spec"], (str, type(None))):
            fields["spawn_spec"] = json.dumps(fields["spawn_spec"])
        self._update("services", service_id, fields)

    # ---- respawn budgets (durable self-healing accounting) ----
    @staticmethod
    def _lineage(service_type: str, job_id: str) -> str:
        return f"{service_type}:{job_id}"

    def incr_respawn_count(self, service_type: str, job_id: str) -> int:
        """Atomically bump and return the (service type, job) lineage's
        respawn count. Durable: a crash-looping config cannot reset its
        budget by crashing the ADMIN too — the restarted admin resumes
        the same counter."""
        lineage = self._lineage(service_type, job_id)
        with self._lock:
            cur = self._exec(
                "UPDATE respawn_budgets SET count=count+1, updated_at=? "
                "WHERE lineage=?", (_now(), lineage))
            if cur.rowcount == 0:
                self._exec(
                    "INSERT INTO respawn_budgets (lineage, count, "
                    "updated_at) VALUES (?,?,?)", (lineage, 1, _now()))
            self._adapter.commit(self._conn)
            row = self._exec(
                "SELECT count FROM respawn_budgets WHERE lineage=?",
                (lineage,), max_rows=1).fetchone()
        return int(row["count"]) if row else 1

    def get_respawn_counts(self) -> Dict[str, int]:
        """All lineages → count (lineage = ``"<type>:<job_id>"``)."""
        return {r["lineage"]: int(r["count"]) for r in self._all(
            "SELECT lineage, count FROM respawn_budgets")}

    # ---- admin lease (single-writer fencing) ----
    def acquire_admin_lease(self, holder: str,
                            ttl_s: float = 15.0
                            ) -> Optional[Dict[str, Any]]:
        """Claim the single-writer admin lease. Exactly one row (id=1)
        exists; ``generation`` is a fencing token that only ever grows.
        Outcomes:

        - no lease yet → insert at generation 1;
        - we already hold it → heartbeat renewed, same generation;
        - held but the heartbeat is older than the TTL the lease was
          GRANTED with (recorded in the row — expiry is the holder's
          contract, not the challenger's opinion) → TAKEOVER: holder
          replaced, generation += 1 (``took_over`` True);
        - held by a live other → ``None`` (the caller must fail fast,
          not spawn a duplicate stack).

        ``ttl_s`` becomes the TTL of the lease THIS caller ends up
        holding. Cross-PROCESS atomicity comes from the database, not
        the in-process lock: the fresh-lease INSERT races on the id=1
        primary key (exactly one boot wins; losers get ``None``), and
        takeovers are conditional on the observed holder+generation.
        """
        now = _now()
        with self._lock:
            row = self._exec("SELECT * FROM admin_lease WHERE id=1",
                             max_rows=1).fetchone()
            if row is None:
                try:
                    self._exec(
                        "INSERT INTO admin_lease (id, holder, "
                        "generation, heartbeat_at, acquired_at, ttl_s) "
                        "VALUES (1,?,?,?,?,?)",
                        (holder, 1, now, now, ttl_s))
                    self._adapter.commit(self._conn)
                except Exception:
                    # two fresh boots raced the id=1 primary key from
                    # separate processes (self._lock cannot cover that)
                    # — if a row exists now, the other boot won and we
                    # are simply fenced; anything else is a real error
                    self._adapter.rollback(self._conn)
                    if self._exec("SELECT 1 FROM admin_lease WHERE "
                                  "id=1", max_rows=1).fetchone() is None:
                        raise
                    return None
                return {"holder": holder, "generation": 1,
                        "took_over": False}
            if row["holder"] == holder:
                self._exec(
                    "UPDATE admin_lease SET heartbeat_at=?, ttl_s=? "
                    "WHERE id=1 AND holder=?", (now, ttl_s, holder))
                self._adapter.commit(self._conn)
                return {"holder": holder,
                        "generation": int(row["generation"]),
                        "took_over": False}
            held_ttl = float(row["ttl_s"] or 0) or ttl_s
            if now - float(row["heartbeat_at"] or 0) <= held_ttl:  # rafiki: noqa[taint-wall-clock-flow] — lease takeover must survive host reboots; monotonic resets to 0 on reboot and would fence takeover out forever
                return None  # live other admin: fenced out
            gen = int(row["generation"]) + 1
            cur = self._exec(
                "UPDATE admin_lease SET holder=?, generation=?, "
                "heartbeat_at=?, acquired_at=?, ttl_s=? WHERE id=1 "
                "AND holder=? AND generation=?",
                (holder, gen, now, now, ttl_s, row["holder"],
                 row["generation"]))
            self._adapter.commit(self._conn)
            if cur.rowcount == 0:
                return None  # raced another takeover: it won
            return {"holder": holder, "generation": gen,
                    "took_over": True}

    def renew_admin_lease(self, holder: str) -> bool:
        """Heartbeat the lease. False = we no longer hold it (a newer
        admin took over) — the caller is FENCED and must stop mutating
        shared state immediately."""
        with self._lock:
            cur = self._exec(
                "UPDATE admin_lease SET heartbeat_at=? WHERE id=1 AND "
                "holder=?", (_now(), holder))
            self._adapter.commit(self._conn)
            return cur.rowcount == 1

    def release_admin_lease(self, holder: str) -> bool:
        """Clean shutdown: zero the heartbeat (instantly expired) but
        KEEP holder + generation — the fencing token must stay
        monotonic across releases, so the next boot takes over at
        generation + 1 rather than restarting at 1."""
        with self._lock:
            cur = self._exec(
                "UPDATE admin_lease SET heartbeat_at=0 WHERE id=1 AND "
                "holder=?", (holder,))
            self._adapter.commit(self._conn)
            return cur.rowcount == 1

    def get_admin_lease(self) -> Optional[Dict[str, Any]]:
        return self._one("SELECT * FROM admin_lease WHERE id=1")

    # ---- online backup ----
    def backup(self, path: str) -> Dict[str, Any]:
        """Snapshot the live store to ``path`` (SQLite online backup
        API; consistent even with concurrent writers). Returns
        {path, bytes}. Operators run this before risky ops — see
        docs/operations.md "Admin death & recovery"."""
        db_file = getattr(self._adapter, "path", None)
        if db_file and db_file != ":memory:":
            # dedicated connection, NO store lock: SQLite's backup API
            # is online by design — holding the store-wide lock for the
            # whole page copy would stall every other caller (including
            # the admin's lease heartbeat) for the backup's duration
            conn = self._adapter.connect()
            try:
                self._adapter.backup(conn, path)
            finally:
                self._adapter.close(conn)
        else:
            # :memory: (or non-file engines): the live connection IS
            # the database — serialize briefly under the lock
            with self._lock:
                self._adapter.backup(self._conn, path)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        return {"path": path, "bytes": size}


def _hash_password(password: str, salt: str) -> str:
    return hashlib.pbkdf2_hmac("sha256", password.encode(),
                               bytes.fromhex(salt), 100_000).hex()
