"""Orbax interop for parameter trees.

SURVEY.md §5.4 names "Orbax checkpoints as the blob format" for the
rebuild's checkpoint story. The repo's native formats are the msgpack
blob (``param_store.py`` — small trees, any backend) and the
per-shard multi-host format (``sharded_ckpt.py`` — scale); this module
bridges to the ECOSYSTEM format so rafiki-tpu checkpoints interoperate
with the rest of the JAX world: export any trained tree as a standard
Orbax checkpoint (loadable by plain ``orbax.checkpoint`` anywhere),
and import Orbax checkpoints produced elsewhere — directly into
shardings when a mesh template is given (Orbax restores each leaf
against the template's sharding, so no host materializes a full tree
it can't hold).
"""

from __future__ import annotations

import os
from typing import Any, Optional


def save_orbax(path: str, tree: Any) -> str:
    """Write ``tree`` as a standard Orbax checkpoint directory at
    ``path`` (created; must not already contain one). Returns the
    absolute path. The result is plain Orbax — any JAX project can
    ``StandardCheckpointer().restore(path)`` it."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree)
    return path


def load_orbax(path: str, template: Optional[Any] = None) -> Any:
    """Restore an Orbax checkpoint.

    ``template`` (optional): a pytree of arrays OR ShapeDtypeStructs
    with shardings — when given, each leaf restores against it (shape/
    dtype checked; sharded leaves land directly in their placements,
    the multi-host-friendly path). Without one, the checkpoint's own
    metadata drives the restore onto host/default devices."""
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if template is None:
            return ckptr.restore(path)
        abstract = jax.tree_util.tree_map(
            lambda x: x if isinstance(x, jax.ShapeDtypeStruct)
            else jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
            template)
        return ckptr.restore(path, abstract)
