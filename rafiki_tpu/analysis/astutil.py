"""Shared AST helpers for lint rules.

The heavy lifting every JAX rule needs is the *traced-function set*:
which ``def``s in this module execute under ``jax.jit`` / ``pjit`` /
``shard_map`` tracing. That is where host-sync and tracer-branch
hazards live — the same call that is free in eager Python is a
device round-trip (or a ConcretizationTypeError) once traced.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

#: decorator / wrapper spellings that mean "this function is traced".
JIT_NAMES = {
    "jit", "jax.jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit",
    "eqx.filter_jit", "nn.jit",
}
SHARD_MAP_NAMES = {
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "shard_map_kernels", "shard_map_checked",
}


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def chain_root(node: ast.AST) -> Optional[ast.AST]:
    """Innermost value of an attribute chain (``a`` for ``a.b.c``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node


def attr_depth(node: ast.Attribute) -> int:
    """Number of attribute hops: ``a.b`` -> 1, ``a.b.c`` -> 2."""
    depth = 0
    cur: ast.AST = node
    while isinstance(cur, ast.Attribute):
        depth += 1
        cur = cur.value
    return depth


def call_name(node: ast.Call) -> Optional[str]:
    return dotted(node.func)


def param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if a.vararg:
        names.append(a.vararg.arg)
    names.extend(p.arg for p in a.kwonlyargs)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _const_strs(node: ast.AST) -> Set[str]:
    """String constants in a literal or tuple/list of literals."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.add(n.value)
    return out


def _const_ints(node: ast.AST) -> Set[int]:
    out: Set[int] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, int):
            out.add(n.value)
    return out


@dataclasses.dataclass
class TracedInfo:
    """How a function came to be traced, plus its static/donated args."""

    fn: ast.AST  # FunctionDef | AsyncFunctionDef
    via: str  # the jit/shard_map spelling that captured it
    static_names: Set[str]
    donated: bool  # any donate_argnums/donate_argnames present
    decorator: Optional[ast.AST] = None  # the decorator node, if any


def _jit_call_info(call: ast.Call, fn: ast.AST) -> Tuple[Set[str], bool]:
    """static_argnames/nums + donation flag from a jit(...) call node."""
    static: Set[str] = set()
    donated = False
    params = param_names(fn)
    for kw in call.keywords:
        if kw.arg in ("static_argnames",):
            static |= _const_strs(kw.value)
        elif kw.arg in ("static_argnums",):
            for i in _const_ints(kw.value):
                if 0 <= i < len(params):
                    static.add(params[i])
        elif kw.arg in ("donate_argnums", "donate_argnames"):
            donated = True
    return static, donated


def _match_traced_decorator(
        node: ast.AST) -> Optional[Tuple[str, Optional[ast.Call], bool]]:
    """Is this decorator a tracing transform? Returns
    ``(spelling, call|None, is_jit)``.

    Matches ``jax.jit``, ``jax.jit(...)`` (decorator-with-args),
    ``functools.partial(jax.jit, ...)``, and the shard_map spellings in
    the same three forms — ``@partial(shard_map_kernels, mesh=...)`` is
    how every in-repo shard_map body is written.
    """
    name = dotted(node)
    if name in JIT_NAMES:
        return name, None, True
    if name in SHARD_MAP_NAMES:
        return name, None, False
    if isinstance(node, ast.Call):
        fname = dotted(node.func)
        if fname in JIT_NAMES:
            return fname, node, True
        if fname in SHARD_MAP_NAMES:
            return fname, node, False
        if fname in ("functools.partial", "partial") and node.args:
            inner = dotted(node.args[0])
            if inner in JIT_NAMES:
                return inner, node, True
            if inner in SHARD_MAP_NAMES:
                return inner, node, False
    return None


def traced_functions(tree: ast.Module) -> Dict[ast.AST, TracedInfo]:
    """All function defs in the module that run under JAX tracing.

    Three capture forms:
    - decorated: ``@jax.jit`` / ``@partial(jax.jit, ...)``
    - wrapped by call: ``step = jax.jit(step_fn)`` or ``jax.jit(f)(x)``
    - handed to shard_map: ``shard_map(f, mesh=...)`` (first arg)
    """
    by_name: Dict[str, ast.AST] = {}
    out: Dict[ast.AST, TracedInfo] = {}
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in fns:
        by_name[fn.name] = fn  # last def wins; fine for lint purposes
        for dec in fn.decorator_list:
            m = _match_traced_decorator(dec)
            if m is None:
                continue
            via, call, is_jit = m
            static, donated = (_jit_call_info(call, fn)
                               if is_jit and call is not None
                               else (set(), False))
            out[fn] = TracedInfo(fn, via, static, donated, decorator=dec)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func)
        is_jit = fname in JIT_NAMES
        is_smap = fname in SHARD_MAP_NAMES
        if not (is_jit or is_smap) or not node.args:
            continue
        target = node.args[0]
        fn = by_name.get(target.id) if isinstance(target, ast.Name) \
            else None  # lambdas and inline expressions aren't analyzed
        if fn is None or fn in out:
            continue
        static, donated = _jit_call_info(node, fn) if is_jit else (set(),
                                                                   False)
        out[fn] = TracedInfo(fn, fname or "", static, donated)
    return out


def body_nodes(fn: ast.AST, skip=()):
    """Walk a function's body WITHOUT descending into the defs in
    ``skip`` — pass the module's traced-function set so a nested def
    that is independently captured (its own ``@jax.jit`` etc.) is
    reported once, under its own entry, not twice. Plain nested defs
    are included: they trace with the parent."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef,
                             ast.AsyncFunctionDef)) and node in skip:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
