"""Forward dataflow over the CFG: taint propagation, path search,
and the :class:`FlowRule` base the path-sensitive rules implement.

Two engines, matched to the two shapes of flow question:

- :func:`path_search` — explicit path enumeration from a program
  point ("is there a path from this ``.acquire()`` to the function
  exit with no ``release()``?", "is this name read again after being
  donated?"). Statement-granular, kill-aware, and finally-disciplined:
  a path that entered a ``finally`` normally cannot leave it on the
  exception continuation (see :mod:`.cfg`). Returns witness paths.

- :class:`TaintEngine` — a label-propagating lattice run to fixpoint
  over the CFG ("does wall-clock time reach a deadline?", "does a hub
  payload field reach subprocess argv?"). State maps variable paths
  (``x``, ``self.deadline``) to a :class:`Taint` carrying the witness
  chain; assignments/arithmetic/casts propagate, sanitizer calls cut,
  rebinding to a clean value kills. Merges keep the first (shortest)
  witness; convergence is judged on key sets only, so loop-carried
  taint stabilizes in O(vars) iterations.

A :class:`FlowRule` declares its ``sources``/``sinks``/``sanitizers``
(human-readable, shown by ``lint --explain``) and an ``example``
snippet, and implements ``check(ctx)`` yielding ``(node, message,
trace)`` triples; the engine attaches location, suppression, and
rendering (text indented steps, SARIF ``codeFlows``).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import (Callable, Dict, Iterator, List, Optional,
                    Sequence, Set, Tuple)

from .cfg import CFG, EDGE_NOTES, Block, _can_raise, build_cfg
from .engine import SEVERITIES, TraceStep

__all__ = [
    "FlowRule", "PathHit", "Taint", "TaintEngine", "all_flow_rules",
    "functions", "get_flow_rule", "has_source", "header_exprs",
    "path_search",
    "register_flow", "tainted_return_helpers",
]


def header_exprs(stmt: ast.AST) -> List[ast.AST]:
    """The parts of a statement that evaluate *at its CFG position*.

    Compound statements sit in a block as terminators but own nested
    bodies that belong to OTHER blocks — predicates must only look at
    the header (test/iterator/context managers), never the body.
    """
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    if isinstance(stmt, ast.Return):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Raise):
        return [n for n in (stmt.exc, stmt.cause) if n is not None]
    if isinstance(stmt, ast.ExceptHandler):
        return [stmt.type] if stmt.type is not None else []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return list(stmt.decorator_list)
    return [stmt]


def _walk_headers(stmt: ast.AST) -> Iterator[ast.AST]:
    for part in header_exprs(stmt):
        yield from ast.walk(part)


# --------------------------------------------------- path search

@dataclasses.dataclass
class PathHit:
    """One witness path: the hit statement plus the steps to it."""

    stmt: ast.AST
    note: str
    #: (anchor node, phrase) pairs from just after the start point to
    #: the hit — branch decisions, exception hops, the hit itself
    steps: List[Tuple[ast.AST, str]]


def _norm_kind(kind: str) -> str:
    return "raise" if kind in ("exc", "raise") else kind


def path_search(cfg: CFG, start_block: Block, start_idx: int, *,
                kill: Callable[[ast.AST], Optional[str]],
                hit: Optional[Callable[[ast.AST], Optional[str]]] = None,
                to_exit: bool = False,
                exit_note: str = "the function can exit here",
                soft_exc_note: Optional[str] = None,
                max_hits: int = 16) -> List[PathHit]:
    """Enumerate paths from (block, stmt index) until ``kill``.

    ``kill(stmt)`` returns falsy (keep walking), ``"hard"``/truthy
    (this statement settles the obligation — stop, including its
    exception path), or ``"soft"`` (the statement settles it ONLY if
    it completes: stop the normal path but keep exploring its
    exception path; with ``to_exit`` and no enclosing try, the
    potential raise itself is an exit witness, noted with
    ``soft_exc_note``). ``hit(stmt)`` returning a note records a
    witness at that statement; with ``to_exit`` an edge into
    ``cfg.exit`` records one anchored at the last statement walked.
    Each distinct hit statement is reported once, with the first
    (BFS-shortest) path as its witness. Exception successors are only
    taken from statements that can actually raise; ``fin:`` fan-out
    edges must match the kind the path entered the finally with.
    """
    hits: List[PathHit] = []
    seen_hit_ids: Set[int] = set()
    # state: (block id, stmt index, finally-entry-kind stack)
    start = (start_block.id, start_idx, ())
    parents: Dict[tuple, Tuple[Optional[tuple], Optional[ast.AST], str]] = {
        start: (None, None, "")}
    by_id = {b.id: b for b in cfg.blocks}
    frontier = [start]
    visited = {start}

    def _steps(state: tuple, final: Tuple[ast.AST, str]
               ) -> List[Tuple[ast.AST, str]]:
        chain: List[Tuple[ast.AST, str]] = []
        cur = state
        while cur is not None:
            parent, anchor, kind = parents[cur]
            if anchor is not None and kind and kind != "flow":
                chain.append((anchor, EDGE_NOTES.get(
                    kind.replace("fin:", ""), kind)))
            cur = parent
        chain.reverse()
        chain.append(final)
        return chain

    def _record(state: tuple, stmt: ast.AST, note: str) -> None:
        if id(stmt) in seen_hit_ids or len(hits) >= max_hits:
            return
        seen_hit_ids.add(id(stmt))
        hits.append(PathHit(stmt, note, _steps(state, (stmt, note))))

    def _push(state: tuple, nxt: tuple, anchor: Optional[ast.AST],
              kind: str) -> None:
        if nxt in visited:
            return
        visited.add(nxt)
        parents[nxt] = (state, anchor, kind)
        frontier.append(nxt)

    def _take_edge(state: tuple, anchor: Optional[ast.AST],
                   succ: Block, kind: str) -> None:
        fin_stack = state[2]
        if kind.startswith("fin:"):
            base = _norm_kind(kind[4:])
            if fin_stack:
                if fin_stack[-1] != base:
                    return  # continuation does not match the entry
                fin_stack = fin_stack[:-1]
            # empty stack: the search started inside this finally —
            # every continuation is plausible
        if succ.id in cfg.finally_entries:
            fin_stack = fin_stack + (_norm_kind(
                kind[4:] if kind.startswith("fin:") else kind),)
        if succ is cfg.exit:
            if to_exit:
                _record(state, anchor if anchor is not None
                        else cfg.fn, exit_note)
            return
        _push(state, (succ.id, 0, fin_stack), anchor, kind)

    while frontier:
        state = frontier.pop(0)
        bid, idx, fin_stack = state
        block = by_id[bid]
        if idx < len(block.stmts):
            stmt = block.stmts[idx]
            if hit is not None:
                note = hit(stmt)
                if note:
                    _record(state, stmt, note)
            verdict = kill(stmt)
            # exception successors are available from any statement
            # that can plausibly raise — unless a hard kill settled
            # the obligation outright
            if verdict != "hard" and \
                    any(_can_raise(p) for p in header_exprs(stmt)):
                exc_succs = [(s, k) for s, k in block.succs
                             if k == "exc"]
                for succ, kind in exc_succs:
                    _take_edge(state, stmt, succ, kind)
                if verdict == "soft" and to_exit and not exc_succs:
                    # no enclosing try: if the settling call raises,
                    # the obligation escapes with the exception
                    _record(state, stmt, soft_exc_note or exit_note)
            if verdict:
                continue
            _push(state, (bid, idx + 1, fin_stack), None, "flow")
            continue
        # past the last statement: leave the block
        anchor = block.stmts[-1] if block.stmts else None
        for succ, kind in block.succs:
            if kind == "exc":
                continue  # taken per raising statement above
            _take_edge(state, anchor, succ, kind)
    return hits


# --------------------------------------------------- taint engine

@dataclasses.dataclass(frozen=True)
class Taint:
    """A tainted value's witness: (line, col, note) hops, source first."""

    steps: Tuple[Tuple[int, int, str], ...]

    def extend(self, node: ast.AST, note: str) -> "Taint":
        step = (getattr(node, "lineno", 0),
                getattr(node, "col_offset", 0), note)
        if self.steps and self.steps[-1][:2] == step[:2]:
            return self  # same-line hop adds noise, not signal
        return Taint(self.steps + (step,))


def _merge_taint(a: Optional[Taint], b: Optional[Taint]
                 ) -> Optional[Taint]:
    if a is None:
        return b
    if b is None:
        return a
    return a if len(a.steps) <= len(b.steps) else b


#: dataflow state: variable path (``x`` / ``self.deadline``) -> Taint
_State = Dict[str, Taint]

#: callables whose RESULT carries their arguments' taint — value-
#: preserving casts and aggregates. Arbitrary calls do NOT propagate
#: argument taint to their result (``cur = self._exec(sql, (now,))``
#: returns a cursor, not the timestamp); method calls on a tainted
#: object still propagate through the function expression itself.
_PASSTHROUGH = {"abs", "bool", "deepcopy", "dict", "float", "int",
                "list", "max", "min", "round", "set", "sorted", "str",
                "sum", "tuple"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class TaintEngine:
    """Fixpoint taint propagation over one function's CFG.

    ``source(node)`` returns a note when the expression node itself
    introduces taint (e.g. a ``time.time()`` call); ``sanitizer(call)``
    returns True when a call's result is clean regardless of its
    arguments (the taint does not flow THROUGH it). After
    :meth:`run`, :meth:`state_before` gives the state at any
    statement and :meth:`eval` judges any expression in that state.
    """

    def __init__(self, cfg: CFG,
                 source: Callable[[ast.AST], Optional[str]],
                 sanitizer: Optional[Callable[[ast.Call], bool]] = None):
        self.cfg = cfg
        self.source = source
        self.sanitizer = sanitizer or (lambda call: False)
        self._before: Dict[int, _State] = {}  # id(stmt) -> state

    # ---- expression evaluation ----

    def eval(self, expr: Optional[ast.AST],
             state: _State) -> Optional[Taint]:
        if expr is None:
            return None
        note = self.source(expr)
        if note:
            return Taint(((expr.lineno, expr.col_offset, note),))
        if isinstance(expr, ast.Call):
            if self.sanitizer(expr):
                return None
            out = self.eval(expr.func, state)
            name = (_dotted(expr.func) or "").rsplit(".", 1)[-1]
            if name in _PASSTHROUGH:
                for part in list(expr.args) + [
                        kw.value for kw in expr.keywords]:
                    out = _merge_taint(out, self.eval(part, state))
            return out
        if isinstance(expr, ast.Name):
            return state.get(expr.id)
        if isinstance(expr, ast.Attribute):
            path = _dotted(expr)
            if path is not None:
                t = state.get(path)
                if t is not None:
                    return t
            return self.eval(expr.value, state)
        if isinstance(expr, ast.Lambda):
            return None  # deferred body: not evaluated here
        out = None
        for child in ast.iter_child_nodes(expr):
            out = _merge_taint(out, self.eval(child, state))
        return out

    # ---- statement transfer ----

    def _assign(self, state: _State, target: ast.AST,
                taint: Optional[Taint], node: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign(state, elt, taint, node)
            return
        if isinstance(target, ast.Starred):
            target = target.value
        if isinstance(target, ast.Subscript):
            # d[k] = v: tainting the whole container would drown later
            # membership/flag reads in noise — keyed sinks (deadline-
            # named keys) are judged at the sink site instead
            return
        path = _dotted(target)
        if path is None:
            return
        if taint is None:
            state.pop(path, None)
        else:
            state[path] = taint.extend(
                node, f"flows into '{path}'")

    def transfer(self, stmt: ast.AST, state: _State) -> None:
        if isinstance(stmt, ast.Assign):
            t = self.eval(stmt.value, state)
            for target in stmt.targets:
                self._assign(state, target, t, stmt)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign(state, stmt.target,
                         self.eval(stmt.value, state), stmt)
        elif isinstance(stmt, ast.AugAssign):
            t = self.eval(stmt.value, state)
            path = _dotted(stmt.target)
            if path is not None:
                t = _merge_taint(t, state.get(path))
                if t is not None:
                    state[path] = t.extend(
                        stmt, f"flows into '{path}'")
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._assign(state, stmt.target,
                         self.eval(stmt.iter, state), stmt)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self._assign(state, item.optional_vars,
                                 self.eval(item.context_expr, state),
                                 stmt)
        elif isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                path = _dotted(target)
                if path is not None:
                    state.pop(path, None)

    # ---- fixpoint ----

    def run(self) -> "TaintEngine":
        cfg = self.cfg
        in_states: Dict[int, _State] = {cfg.entry.id: {}}
        worklist = [cfg.entry]
        while worklist:
            block = worklist.pop(0)
            state = dict(in_states.get(block.id, {}))
            for stmt in block.stmts:
                self.transfer(stmt, state)
            for succ, _kind in block.succs:
                if succ is cfg.exit:
                    continue
                prev = in_states.get(succ.id)
                if prev is None:
                    in_states[succ.id] = dict(state)
                    worklist.append(succ)
                    continue
                grew = False
                for var, taint in state.items():
                    if var not in prev:
                        prev[var] = taint
                        grew = True
                if grew and succ not in worklist:
                    worklist.append(succ)
        # final pass: record the state before every statement
        for block in cfg.blocks:
            state = dict(in_states.get(block.id, {}))
            for stmt in block.stmts:
                self._before[id(stmt)] = dict(state)
                self.transfer(stmt, state)
        return self

    def state_before(self, stmt: ast.AST) -> _State:
        return self._before.get(id(stmt), {})

    def taint_at(self, expr: Optional[ast.AST],
                 stmt: ast.AST) -> Optional[Taint]:
        """Judge ``expr`` (part of ``stmt``) in the state before it."""
        return self.eval(expr, self.state_before(stmt))


def tainted_return_helpers(
        tree: ast.Module,
        source: Callable[[ast.AST], Optional[str]],
        sanitizer: Optional[Callable[[ast.Call], bool]] = None
) -> Dict[str, Taint]:
    """Module-local helpers whose RETURN value is tainted — one level
    of interprocedural reach (``def now(): return time.time()`` makes
    ``now()`` call sites sources). Methods register both ``name`` and
    ``self.name``."""
    out: Dict[str, Taint] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # a fixpoint per function is the expensive part — skip
        # functions that return nothing or contain no source at all
        if not any(isinstance(sub, ast.Return) and sub.value is not None
                   for sub in ast.walk(node)):
            continue
        if not any(source(sub) for sub in ast.walk(node)):
            continue
        eng = TaintEngine(build_cfg(node), source, sanitizer).run()
        for block in eng.cfg.blocks:
            for stmt in block.stmts:
                if not isinstance(stmt, ast.Return):
                    continue
                t = eng.taint_at(stmt.value, stmt)
                if t is None:
                    continue
                t = t.extend(stmt, f"returned from '{node.name}'")
                out[node.name] = _merge_taint(out.get(node.name), t)
                out["self." + node.name] = out[node.name]
    return out


# --------------------------------------------------- FlowRule base

class FlowRule:
    """Base class for path-sensitive (CFG/dataflow) rules.

    Like :class:`~rafiki_tpu.analysis.engine.Rule` but ``check``
    yields ``(node, message, trace)`` triples, where ``trace`` is a
    tuple of :class:`~rafiki_tpu.analysis.engine.TraceStep` rendering
    the source→sink witness. ``sources``/``sinks``/``sanitizers`` are
    one-line human descriptions (``lint --explain``); ``example`` is
    a self-contained snippet the rule fires on, used to print an
    example trace.
    """

    id: str = ""
    category: str = ""
    severity: str = "error"
    description: str = ""
    sources: Tuple[str, ...] = ()
    sinks: Tuple[str, ...] = ()
    sanitizers: Tuple[str, ...] = ()
    example: str = ""

    def check(self, ctx):  # pragma: no cover - interface
        raise NotImplementedError
        yield

    # -- shared helpers --

    @staticmethod
    def trace_from_taint(taint: Taint,
                         sink_node: ast.AST,
                         sink_note: str) -> Tuple[TraceStep, ...]:
        steps = [TraceStep(line, col, note)
                 for line, col, note in taint.steps]
        steps.append(TraceStep(sink_node.lineno,
                               sink_node.col_offset, sink_note))
        return tuple(steps)

    @staticmethod
    def trace_from_path(source_node: ast.AST, source_note: str,
                        hit: PathHit) -> Tuple[TraceStep, ...]:
        steps = [TraceStep(source_node.lineno,
                           source_node.col_offset, source_note)]
        for anchor, phrase in hit.steps:
            steps.append(TraceStep(getattr(anchor, "lineno", 0),
                                   getattr(anchor, "col_offset", 0),
                                   phrase))
        # collapse consecutive same-line steps
        out: List[TraceStep] = []
        for step in steps:
            if out and (out[-1].line, out[-1].col) == (step.line,
                                                       step.col):
                out[-1] = step if step is steps[-1] else out[-1]
                continue
            out.append(step)
        return tuple(out)


def functions(ctx) -> Iterator[Tuple[ast.AST, CFG]]:
    """Every function in the module with its (cached) CFG."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, ctx.cfg(node)


def has_source(fn: ast.AST,
               source: Callable[[ast.AST], Optional[str]]) -> bool:
    """Does any node of this function introduce taint? A single AST
    walk — taint rules call this before paying for a fixpoint, since
    a function with no source cannot reach any sink."""
    return any(source(sub) for sub in ast.walk(fn))


_FLOW_REGISTRY: Dict[str, FlowRule] = {}


def register_flow(cls):
    """Class decorator adding a flow rule to the registry."""
    if not cls.id:
        raise ValueError(f"flow rule {cls.__name__} has no id")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"rule {cls.id}: bad severity {cls.severity!r}")
    if cls.id in _FLOW_REGISTRY:
        raise ValueError(f"duplicate flow rule id {cls.id!r}")
    _FLOW_REGISTRY[cls.id] = cls()
    return cls


def all_flow_rules() -> Dict[str, FlowRule]:
    from . import rules  # noqa: F401 — import side effect registers

    return dict(_FLOW_REGISTRY)


def get_flow_rule(rule_id: str) -> FlowRule:
    rules = all_flow_rules()
    if rule_id not in rules:
        raise KeyError(
            f"unknown flow rule {rule_id!r} "
            f"(known: {', '.join(sorted(rules))})")
    return rules[rule_id]
