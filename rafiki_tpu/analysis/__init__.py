"""``rafiki_tpu.analysis`` — domain-aware static analysis (``rafiki-tpu lint``).

Python's type system and generic linters cannot see the hazard classes
that actually break a hand-your-model-over platform like this one:
host-device syncs hiding inside ``jax.jit`` bodies, scalar branches on
tracers, module state mutated from serving threads without the lock the
rest of the class holds, and ``except:`` blocks that eat the only
evidence of a fleet-wide regression. This package is an AST-based rule
engine targeting exactly those classes, run over ``rafiki_tpu/`` itself
by a tier-1 test (``tests/test_lint.py``) so the repo stays self-clean
and every future PR is gated.

Two scopes:

- **per-module rules** (:class:`Rule`) see one file at a time via
  :func:`analyze_paths` / :func:`analyze_source`;
- **project rules** (:class:`ProjectRule`, ``lint --project``) see the
  whole package at once via :func:`analyze_project` — cross-layer
  contracts (hub verb parity, lock ordering across classes, metric
  catalog drift) live here; see ``docs/linting.md``.

``# rafiki: noqa[rule-id]`` on a finding's line suppresses it in both
scopes — inside the comment syntax of whatever file the finding lands
in (Python, C++, Markdown, HTML).
"""

from .engine import (Finding, Rule, all_rules, analyze_paths,
                     analyze_source, get_rule, register)
from .project import (ProjectContext, ProjectRule, all_project_rules,
                      analyze_project, get_project_rule,
                      register_project)

__all__ = [
    "Finding",
    "ProjectContext",
    "ProjectRule",
    "Rule",
    "all_project_rules",
    "all_rules",
    "analyze_paths",
    "analyze_project",
    "analyze_source",
    "get_project_rule",
    "get_rule",
    "register",
    "register_project",
]
