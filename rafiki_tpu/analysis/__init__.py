"""``rafiki_tpu.analysis`` — domain-aware static analysis (``rafiki-tpu lint``).

Python's type system and generic linters cannot see the hazard classes
that actually break a hand-your-model-over platform like this one:
host-device syncs hiding inside ``jax.jit`` bodies, scalar branches on
tracers, module state mutated from serving threads without the lock the
rest of the class holds, and ``except:`` blocks that eat the only
evidence of a fleet-wide regression. This package is an AST-based rule
engine targeting exactly those classes, run over ``rafiki_tpu/`` itself
by a tier-1 test (``tests/test_lint.py``) so the repo stays self-clean
and every future PR is gated.

Public API:

- :func:`analyze_paths` / :func:`analyze_source` — run all (or selected)
  rules, returning :class:`Finding` objects.
- :class:`Rule`, :func:`register` — the rule framework; see
  ``docs/linting.md`` for how to add a rule.
- ``# rafiki: noqa[rule-id]`` on a finding's line suppresses it.
"""

from .engine import (Finding, Rule, all_rules, analyze_paths,
                     analyze_source, get_rule, register)

__all__ = [
    "Finding",
    "Rule",
    "all_rules",
    "analyze_paths",
    "analyze_source",
    "get_rule",
    "register",
]
