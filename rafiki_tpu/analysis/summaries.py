"""Per-function shared-state access summaries with must-hold locksets.

The second half of the race detector (:mod:`.rules.project_threads`):
where :mod:`.threads` answers *which threads run this function*, this
module answers *what shared state it touches and which locks it
provably holds at each touch*. For every project function we record
each ``self.field`` / module-global access as an :class:`Access`
annotated with its **effective lockset**, built from three sources:

1. **``with``-scope locks** — an AST walk tracking ``with <lock>:``
   nesting, with lock identity resolved through the project-wide
   :class:`~.rules.project_locks._LockNames` table (``Condition(lock)``
   aliases the wrapped lock, MRO-aware for inherited lock attrs).
2. **Manual ``acquire()``/``release()``** — a forward must-dataflow
   over the function's CFG (meet = intersection, the same modeling the
   ``lock-release-path`` flow rule uses): a lock counts as held at a
   statement only when EVERY path to it acquired and did not release.
   Only functions that actually call ``.acquire`` on a named lock pay
   for the CFG.
3. **Interprocedural ``held_in``** — the locks held at *every*
   resolved call site of the function (intersection over callers,
   callers' own ``held_in`` included), computed as a descending
   fixpoint over the call graph. ``foo_locked()`` helpers called under
   a lock inherit it; a helper reachable both locked and bare inherits
   nothing, which is exactly the hazard.

Fields that are **internally synchronized** never produce accesses:
lock/Condition/Semaphore objects themselves, ``queue.Queue`` family,
``threading.Event``, ``collections.deque`` (GIL-atomic append/pop),
``StatsMap`` and obs-registry instruments (counter/gauge/histogram own
their locking), plus lock-named attributes. Unresolvable fields
(never assigned in any project class of the MRO) are skipped too.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from .astutil import dotted
from .cfg import build_cfg
from .project import ClassInfo, FunctionInfo, ProjectContext
from .rules.concurrency import _LOCK_CTORS, _MUTATORS, _local_bindings
from .rules.project_locks import _LockNames
from .threads import ThreadModel, walk_own

#: constructors whose instances synchronize internally — accesses to
#: fields holding one are never race candidates
_SYNC_CTORS = _LOCK_CTORS | {
    "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Event", "Event", "threading.local",
    "queue.Queue", "Queue", "queue.SimpleQueue", "SimpleQueue",
    "queue.LifoQueue", "LifoQueue", "queue.PriorityQueue",
    "PriorityQueue", "collections.deque", "deque",
    "StatsMap",
}

#: obs-registry instrument factories: ``self.c = metrics.counter(...)``
_INSTRUMENT_ATTRS = {"counter", "gauge", "histogram"}

#: field names that are synchronized (or synchronization) by contract
#: in this codebase, whatever the constructor spelling
_SYNC_NAME_RE = re.compile(
    r"(?:^|_)(?:lock|mutex|sem|cv|cond|event)s?(?:_|$)|"
    r"^_?(?:stats|metrics|registry|traces)$")

#: module-level constructors that make a global worth tracking
_MUTABLE_CTORS = {"dict", "list", "set", "collections.defaultdict",
                  "defaultdict", "collections.OrderedDict",
                  "OrderedDict", "collections.deque", "deque",
                  "Counter", "collections.Counter"}


@dataclasses.dataclass(frozen=True)
class Access:
    """One read/write of a shared target, with its effective lockset."""

    target: str   # ``mod:Class.field`` or ``mod:global``
    kind: str     # "read" | "write" | "rmw"
    func: str     # qualname of the accessing function
    path: str
    line: int
    col: int
    locks: frozenset  # effective must-hold lockset at this point
    #: a bare ``self.f = <constant>`` rebind — GIL-atomic, so a
    #: write/read pair on it is a benign flag handoff, not a race
    atomic: bool = False


class AccessSummaries:
    """Shared-state accesses for every function of one project."""

    def __init__(self, project: ProjectContext, model: ThreadModel):
        self.project = project
        self.model = model
        self.names = _LockNames(project)
        #: target -> accesses (effective locksets already folded in)
        self.by_target: Dict[str, List[Access]] = {}
        #: callee qualname -> [(caller qualname, locks at call site)]
        self._caller_edges: Dict[str, List[Tuple[str, frozenset]]] = {}
        #: function qualname -> locks held at every resolved call site
        self.held_in: Dict[str, frozenset] = {}
        self._field_kind: Dict[str, Dict[str, str]] = {}
        self._raw: List[Access] = []
        self._globals: Dict[str, Set[str]] = {
            mod: self._module_globals(ctx.tree)
            for mod, ctx in project.modules.items()}
        for q in sorted(model.functions):
            self._scan_function(model.functions[q])
        self._fixpoint_held_in()
        for a in self._raw:
            eff = a.locks | self.held_in.get(a.func, frozenset())
            self.by_target.setdefault(a.target, []).append(
                dataclasses.replace(a, locks=eff))

    # ---- field classification ----

    def _class_field_kinds(self, info: ClassInfo) -> Dict[str, str]:
        """``attr -> "plain" | "sync"`` for fields assigned anywhere
        in the class body (sync wins when both are seen)."""
        q = info.qualname
        if q in self._field_kind:
            return self._field_kind[q]
        kinds: Dict[str, str] = {}
        for node in ast.walk(info.node):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets, value = [node.target], node.value
            for t in targets:
                path = dotted(t)
                if not (path and path.startswith("self.") and
                        path.count(".") == 1):
                    continue
                attr = path[5:]
                sync = _SYNC_NAME_RE.search(attr) is not None
                if isinstance(value, ast.Call):
                    ctor = dotted(value.func)
                    if ctor in _SYNC_CTORS:
                        sync = True
                    elif isinstance(value.func, ast.Attribute) and \
                            value.func.attr in _INSTRUMENT_ATTRS:
                        sync = True
                if sync or kinds.get(attr) != "sync":
                    kinds[attr] = "sync" if sync else \
                        kinds.get(attr, "plain")
                if sync:
                    kinds[attr] = "sync"
        self._field_kind[q] = kinds
        return kinds

    def _field_target(self, fi: FunctionInfo,
                      attr: str) -> Optional[str]:
        """Canonical ``mod:Class.attr`` for a ``self.attr`` access —
        keyed on the most-base project class assigning the field, so a
        subclass write and a base-class read meet on one target. None
        for sync fields, method references, and unknown attrs."""
        if fi.cls is None:
            return None
        owner: Optional[ClassInfo] = None
        for c in self.project.class_mro(fi.cls):
            if attr in c.methods:
                return None  # bound-method reference, not data
            kinds = self._class_field_kinds(c)
            if attr in kinds:
                if kinds[attr] == "sync":
                    return None
                owner = c
        if owner is None:
            return None
        return f"{owner.qualname}.{attr}"

    @staticmethod
    def _module_globals(tree: ast.Module) -> Set[str]:
        out: Set[str] = set()
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            mutable = isinstance(v, (ast.Dict, ast.List, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp)) or (
                isinstance(v, ast.Call)
                and dotted(v.func) in _MUTABLE_CTORS)
            if not mutable:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        return out

    # ---- per-function scan ----

    def _scan_function(self, fi: FunctionInfo) -> None:
        self._locals = _local_bindings(fi.node)
        self._manual: Dict[int, frozenset] = {}
        if self._has_manual_acquire(fi):
            self._manual = _manual_locksets(fi, self.names)
        for stmt in fi.node.body:
            self._scan(fi, stmt, frozenset())

    def _has_manual_acquire(self, fi: FunctionInfo) -> bool:
        for node in walk_own(fi.node):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "acquire" and \
                    self.names.resolve(fi, node.func.value):
                return True
        return False

    def _scan(self, fi: FunctionInfo, node: ast.AST,
              held: frozenset) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested defs are scanned as their own entries
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                self._scan(fi, item.context_expr, held)
                lid = self.names.resolve(fi, item.context_expr)
                if lid is not None:
                    inner = inner | {lid}
            for stmt in node.body:
                self._scan(fi, stmt, inner)
            return
        if isinstance(node, ast.AugAssign):
            self._record_store(fi, node.target, "rmw", held, node)
            self._scan(fi, node.value, held)
            return
        if isinstance(node, ast.Assign):
            atomic = isinstance(node.value, ast.Constant)
            for t in node.targets:
                self._record_store(fi, t, "write", held, node,
                                   atomic=atomic)
            self._scan(fi, node.value, held)
            return
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name and isinstance(node.func, ast.Attribute):
                if node.func.attr in _MUTATORS:
                    self._record_access(fi, node.func.value, "write",
                                        held, node)
                elif node.func.attr in ("acquire", "release") and \
                        self.names.resolve(fi, node.func.value):
                    # lock-protocol calls are not data accesses
                    for arg in node.args:
                        self._scan(fi, arg, held)
                    return
            if name:
                target = self.project.resolve_call(fi, node)
                if target is not None and \
                        target.qualname in self.model.functions:
                    eff = held | self._manual.get(id(node),
                                                  frozenset())
                    self._caller_edges.setdefault(
                        target.qualname, []).append(
                            (fi.qualname, eff))
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load):
            self._record_access(fi, node, "read", held, node)
        elif isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load):
            self._record_access(fi, node, "read", held, node)
        for child in ast.iter_child_nodes(node):
            self._scan(fi, child, held)

    def _record_store(self, fi: FunctionInfo, target: ast.AST,
                      kind: str, held: frozenset, anchor: ast.AST,
                      atomic: bool = False) -> None:
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value  # d[k] = v mutates d
        if base is not target:
            atomic = False  # container mutation, not a rebind
        if isinstance(base, (ast.Tuple, ast.List)):
            for el in base.elts:
                self._record_store(fi, el, kind, held, anchor)
            return
        self._record_access(fi, base, kind, held, anchor,
                            atomic=atomic)

    def _record_access(self, fi: FunctionInfo, node: ast.AST,
                       kind: str, held: frozenset, anchor: ast.AST,
                       atomic: bool = False) -> None:
        target: Optional[str] = None
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            target = self._field_target(fi, node.attr)
        elif isinstance(node, ast.Name):
            if node.id in self._globals.get(fi.module, ()) and \
                    node.id not in self._locals:
                target = f"{fi.module}:{node.id}"
        if target is None:
            return
        eff = held | self._manual.get(id(anchor), frozenset())
        ctx = self.project.modules.get(fi.module)
        self._raw.append(Access(
            target, kind, fi.qualname,
            ctx.path if ctx else "", anchor.lineno,
            anchor.col_offset, eff, atomic))

    # ---- interprocedural held_in ----

    def _fixpoint_held_in(self) -> None:
        """Descending fixpoint: ``held_in(f)`` = intersection over
        resolved call sites of (locks at the site ∪ caller's own
        ``held_in``). No callers -> nothing assumed; a caller cycle
        with no outside entry also decays to nothing."""
        state: Dict[str, Optional[frozenset]] = {}
        for q in self.model.functions:
            state[q] = None if q in self._caller_edges else frozenset()
        changed = True
        while changed:
            changed = False
            for q, edges in self._caller_edges.items():
                vals = [held | state[caller]
                        for caller, held in edges
                        if state.get(caller) is not None]
                new: Optional[frozenset]
                if vals:
                    new = frozenset.intersection(*vals)
                else:
                    new = state[q]
                if new != state[q]:
                    state[q] = new
                    changed = True
        self.held_in = {q: (v if v is not None else frozenset())
                        for q, v in state.items()}


# ---- manual acquire/release must-dataflow ----

def _manual_locksets(fi: FunctionInfo,
                     names: _LockNames) -> Dict[int, frozenset]:
    """``id(node) -> must-held manual locks`` for every AST node of
    the function, from a forward must-dataflow over the CFG (gen at
    ``.acquire()``, kill at ``.release()``, meet = intersection)."""
    cfg = build_cfg(fi.node)

    def events(stmt: ast.AST) -> List[Tuple[str, str]]:
        out = []
        for node in _header_nodes(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("acquire", "release"):
                lid = names.resolve(fi, node.func.value)
                if lid is not None:
                    out.append((node.func.attr, lid))
        return out

    # block in-states: ⊤ (None) until reached; entry = ∅
    n = len(cfg.blocks)
    in_state: List[Optional[frozenset]] = [None] * n
    in_state[cfg.entry.id] = frozenset()
    work = [cfg.entry]
    while work:
        block = work.pop()
        state = in_state[block.id]
        assert state is not None
        for stmt in block.stmts:
            for op, lid in events(stmt):
                state = (state | {lid}) if op == "acquire" \
                    else (state - {lid})
        for succ, _kind in block.succs:
            prev = in_state[succ.id]
            new = state if prev is None else (prev & state)
            if new != prev:
                in_state[succ.id] = new
                work.append(succ)

    held_at: Dict[int, frozenset] = {}
    for block in cfg.blocks:
        state = in_state[block.id]
        if state is None:
            continue  # unreachable
        for stmt in block.stmts:
            for node in _header_nodes(stmt):
                held_at.setdefault(id(node), state)
            for op, lid in events(stmt):
                state = (state | {lid}) if op == "acquire" \
                    else (state - {lid})
    return held_at


#: compound statements whose bodies live in their own CFG blocks —
#: only their header expressions belong to the statement itself
_COMPOUND = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.With,
             ast.AsyncWith, ast.Try, ast.Match)


def _header_nodes(stmt: ast.AST) -> List[ast.AST]:
    """The nodes evaluated *as part of this CFG statement* — for a
    compound, the test/iter/context expressions, not the body."""
    if not isinstance(stmt, _COMPOUND):
        out = [stmt]
        for node in ast.walk(stmt):
            if node is not stmt:
                out.append(node)
        return out
    headers: List[ast.AST] = [stmt]
    exprs: List[ast.AST] = []
    if isinstance(stmt, (ast.If, ast.While)):
        exprs = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        exprs = [stmt.iter, stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        exprs = [item.context_expr for item in stmt.items]
    elif isinstance(stmt, ast.Match):
        exprs = [stmt.subject]
    for e in exprs:
        headers.extend(ast.walk(e))
    return headers
