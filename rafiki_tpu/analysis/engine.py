"""Rule framework + driver for ``rafiki-tpu lint``.

The engine is deliberately tiny: a rule is a class with an ``id``, a
``severity``, and a ``check(ctx)`` generator over one parsed module.
Everything stateful (source text, AST, parent links, suppression
comments) lives in :class:`ModuleContext`, built once per file and
shared by every rule — rules never re-read the file or re-parse.

Suppression follows the repo-wide comment dialect::

    risky_line()  # rafiki: noqa[silent-except]
    other_line()  # rafiki: noqa          (blanket — any rule)

A suppression must sit on the finding's own line (or the first line of
the multi-line statement that produced it); file-wide opt-outs are
intentionally not offered — they rot.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

#: suppression comment: ``# rafiki: noqa`` or ``# rafiki: noqa[a, b]``.
#: The lookahead rejects malformed forms (``noqa[rule`` without ``]``,
#: ``noqaX``) rather than silently widening them to a blanket
#: suppression of every rule on the line.
_NOQA_RE = re.compile(r"#\s*rafiki:\s*noqa(?:\[([^\]]*)\]|(?![\w\[-]))")

SEVERITIES = ("error", "warning")

#: retired rule id -> the successor ids a legacy suppression still
#: covers. PR 18 replaced the per-module Eraser-vote rules with the
#: interprocedural race detector; every ``# rafiki: noqa[...]`` written
#: against the old ids keeps suppressing the new rules on its line —
#: a rename must never silently turn a documented suppression into
#: a no-op (or the suppressed line into a CI failure).
RULE_ALIASES: Dict[str, tuple] = {
    "inconsistent-lock": ("shared-state-race", "atomic-rmw-race"),
    "thread-unlocked-global": ("shared-state-race", "atomic-rmw-race"),
}


def suppression_matches(rule_id: str, ids: frozenset) -> bool:
    """Does a ``noqa[ids]`` set silence ``rule_id``? Empty = blanket;
    retired ids silence their :data:`RULE_ALIASES` successors."""
    if not ids or rule_id in ids:
        return True
    return any(rule_id in RULE_ALIASES.get(old, ()) for old in ids)


@dataclasses.dataclass(frozen=True)
class TraceStep:
    """One hop of a flow finding's source→sink witness path.

    ``path`` is empty for single-file flow traces (the finding's own
    file is implied); project-scope thread traces set it because a
    call chain crosses modules.
    """

    line: int
    col: int
    note: str
    path: str = ""


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic, pinned to a file location.

    Flow-rule findings additionally carry ``trace`` — the witness
    path from source to sink, rendered as indented steps in text
    output and as ``codeFlows`` in SARIF. Race findings instead carry
    ``threads``: ``(label, steps)`` pairs, one stack per thread
    context, rendered as paired traces in text and as two
    ``threadFlows`` inside one ``codeFlow`` in SARIF.
    """

    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    trace: tuple = ()
    threads: tuple = ()

    def format(self) -> str:
        head = (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.severity}] {self.rule}: {self.message}")
        lines = [head]
        lines += [f"    {i}. line {s.line}:{s.col + 1}: {s.note}"
                  for i, s in enumerate(self.trace, 1)]
        for label, steps in self.threads:
            lines.append(f"    thread [{label}]:")
            for i, s in enumerate(steps, 1):
                where = (f"{s.path}:{s.line}" if s.path
                         else f"line {s.line}")
                lines.append(f"      {i}. {where}: {s.note}")
        return "\n".join(lines)

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class ModuleContext:
    """Everything a rule may inspect about one module, parsed once."""

    def __init__(self, source: str, path: str):
        self.source = source
        self.path = path
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        # parent links: rules constantly ask "am I inside X?"
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self._noqa = _collect_noqa(source)
        self._traced = None  # lazy; see traced()
        self._cfgs: Dict[ast.AST, object] = {}  # lazy; see cfg()
        #: scratch cache for rule-computed module facts (e.g. the jit
        #: callables table both jit flow rules need) — keyed by the
        #: computing module's own name, shared across rules
        self.memo: Dict[str, object] = {}

    def traced(self):
        """The module's traced-function map
        (:func:`rafiki_tpu.analysis.astutil.traced_functions`),
        computed once and shared by every JAX rule."""
        if self._traced is None:
            from .astutil import traced_functions

            self._traced = traced_functions(self.tree)
        return self._traced

    def cfg(self, fn: ast.AST):
        """The function's control-flow graph
        (:func:`rafiki_tpu.analysis.cfg.build_cfg`), built once and
        shared by every flow rule."""
        if fn not in self._cfgs:
            from .cfg import build_cfg

            self._cfgs[fn] = build_cfg(fn)
        return self._cfgs[fn]

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(
            self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def suppressed(self, rule_id: str, line: int) -> bool:
        ids = self._noqa.get(line)
        if ids is None:
            return False
        return suppression_matches(rule_id, ids)


def _collect_noqa(source: str) -> Dict[int, frozenset]:
    """Map line number -> suppressed rule ids (empty set = blanket).

    Uses the tokenizer, not a per-line regex, so a ``# rafiki: noqa``
    inside a string literal is NOT a suppression.
    """
    out: Dict[int, frozenset] = {}
    try:
        tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _NOQA_RE.search(tok.string)
            if not m:
                continue
            ids = frozenset(
                part.strip() for part in (m.group(1) or "").split(",")
                if part.strip())
            out[tok.start[0]] = ids
    except tokenize.TokenError:
        pass  # unterminated string etc. — the parse error is reported
    return out


class Rule:
    """Base class for lint rules.

    Subclasses set ``id`` (kebab-case, stable — it is the suppression
    key), ``category`` (``jax`` | ``concurrency`` | ``robustness``),
    ``severity``, and a one-line ``description`` (shown by
    ``lint --list-rules`` and used in docs). ``check`` yields
    ``(node, message)`` pairs; the engine attaches location, severity,
    and suppression handling.
    """

    id: str = ""
    category: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, ctx: ModuleContext):  # pragma: no cover - interface
        raise NotImplementedError
        yield  # noqa: unreachable — marks this as a generator


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule to the global registry."""
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"rule {cls.id}: bad severity {cls.severity!r}")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls()
    return cls


def all_rules() -> Dict[str, Rule]:
    """The registry, loading the built-in rule modules on first use."""
    from . import rules  # noqa: F401 — import side effect registers

    return dict(_REGISTRY)


def get_rule(rule_id: str) -> Rule:
    rules = all_rules()
    if rule_id not in rules:
        raise KeyError(
            f"unknown rule {rule_id!r} (known: {', '.join(sorted(rules))})")
    return rules[rule_id]


def _resolve_rules(select: Optional[Sequence[str]]) -> List[Rule]:
    """Module + flow rules, by id or all of them.

    Flow rules (:mod:`.dataflow`) live in their own registry but run
    in the same per-file pass — so ``--changed-only`` and fixture
    isolation scope them exactly like per-module rules.
    """
    from .dataflow import all_flow_rules

    rules = all_rules()
    flow_rules = all_flow_rules()
    if select is None:
        return list(rules.values()) + list(flow_rules.values())
    out = []
    for rule_id in select:
        if rule_id in rules:
            out.append(rules[rule_id])
        elif rule_id in flow_rules:
            out.append(flow_rules[rule_id])
        else:
            known = sorted(set(rules) | set(flow_rules))
            raise KeyError(f"unknown rule {rule_id!r} "
                           f"(known: {', '.join(known)})")
    return out


def analyze_source(source: str, path: str = "<string>",
                   select: Optional[Sequence[str]] = None,
                   with_suppressed: bool = False) -> List[Finding]:
    """Run rules over one module's source; returns sorted findings.

    ``with_suppressed`` keeps ``# rafiki: noqa``-silenced findings in
    the result (used by the suppression tests and ``--show-suppressed``).
    """
    try:
        ctx = ModuleContext(source, path)
    except SyntaxError as e:
        return [Finding("parse-error", "error", path, e.lineno or 1,
                        (e.offset or 1) - 1,
                        f"could not parse: {e.msg}")]
    findings: List[Finding] = []
    for rule in _resolve_rules(select):
        for item in rule.check(ctx):
            # module rules yield (node, message); flow rules yield
            # (node, message, trace)
            node, message = item[0], item[1]
            trace = tuple(item[2]) if len(item) > 2 else ()
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
            if not with_suppressed and ctx.suppressed(rule.id, line):
                continue
            findings.append(Finding(rule.id, rule.severity, path,
                                    line, col, message, trace))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


#: directories never worth descending into when walking a tree
_SKIP_DIRS = {"__pycache__", ".git", ".eggs", "build", "dist",
              "node_modules"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        if not os.path.isdir(path):
            # fail loudly per-path: one typo'd argument must not make
            # the gate report "clean" on a tree it never visited
            raise OSError(f"no such file or directory: {path!r}")
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS
                             and not d.endswith(".egg-info"))
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def analyze_paths(paths: Iterable[str],
                  select: Optional[Sequence[str]] = None,
                  with_suppressed: bool = False) -> List[Finding]:
    """Run rules over files/trees; nonexistent paths raise ``OSError``."""
    for path in paths:
        # validate every argument BEFORE analyzing any: a typo'd CI
        # argument must fail fast, not after a full-package pass
        if not os.path.isfile(path) and not os.path.isdir(path):
            raise OSError(f"no such file or directory: {path!r}")
    findings: List[Finding] = []
    seen = False
    for path in iter_python_files(paths):
        seen = True
        with open(path, "rb") as f:
            raw = f.read()
        try:
            source = raw.decode("utf-8")
        except UnicodeDecodeError as e:
            # a finding, not a crash: the gate must report the file and
            # exit 1, not unwind with a traceback
            findings.append(Finding("parse-error", "error", path, 1, 0,
                                    f"not valid UTF-8: {e}"))
            continue
        findings.extend(analyze_source(source, path, select=select,
                                       with_suppressed=with_suppressed))
    if not seen:
        raise OSError(f"no python files under {list(paths)!r}")
    return findings


def render_text(findings: Sequence[Finding]) -> str:
    lines = [f.format() for f in findings]
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    lines.append(f"{len(findings)} finding(s): {n_err} error(s), "
                 f"{n_warn} warning(s)")
    return "\n".join(lines)


def render_sarif(findings: Sequence[Finding]) -> str:
    """SARIF 2.1.0 — the interchange format editors and CI annotation
    APIs (GitHub code scanning, VS Code SARIF viewer) consume.

    One run, one driver; every rule that produced a finding gets a
    ``rules`` entry with its description so viewers can show it inline.
    Paths are emitted relative to the working directory when possible —
    SARIF consumers resolve relative URIs against the repo root.
    """
    rule_meta: Dict[str, Dict[str, object]] = {}

    def _describe(rule_id: str) -> None:
        if rule_id in rule_meta:
            return
        from .dataflow import all_flow_rules
        from .project import all_project_rules

        rule = all_rules().get(rule_id) or \
            all_project_rules().get(rule_id) or \
            all_flow_rules().get(rule_id)
        entry: Dict[str, object] = {"id": rule_id}
        if rule is not None:
            entry["shortDescription"] = {"text": rule.description}
            entry["properties"] = {"category": rule.category}
        rule_meta[rule_id] = entry

    results = []
    for f in findings:
        _describe(f.rule)
        path = f.path
        if os.path.isabs(path):
            try:
                path = os.path.relpath(path)
            except ValueError:  # different drive (windows) — keep abs
                pass
        uri = path.replace(os.sep, "/")
        result: Dict[str, object] = {
            "ruleId": f.rule,
            "level": f.severity,  # SARIF levels include error/warning
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": uri},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
        }
        def _step_loc(s: TraceStep) -> Dict[str, object]:
            step_uri = uri
            if s.path:
                p = s.path
                if os.path.isabs(p):
                    try:
                        p = os.path.relpath(p)
                    except ValueError:
                        pass
                step_uri = p.replace(os.sep, "/")
            return {
                "physicalLocation": {
                    "artifactLocation": {"uri": step_uri},
                    "region": {"startLine": max(s.line, 1),
                               "startColumn": s.col + 1},
                },
                "message": {"text": s.note},
            }

        if f.trace:
            # the witness path: codeFlows for flow-aware viewers,
            # relatedLocations for everything else
            step_locs = [_step_loc(s) for s in f.trace]
            result["codeFlows"] = [{"threadFlows": [{
                "locations": [{"location": loc} for loc in step_locs],
            }]}]
            result["relatedLocations"] = step_locs
        elif f.threads:
            # a race: ONE codeFlow whose threadFlows are the two
            # stacks — one per thread context — exactly the shape
            # SARIF reserves for concurrent witnesses
            thread_flows = []
            related = []
            for label, steps in f.threads:
                locs = [_step_loc(s) for s in steps]
                thread_flows.append({
                    "id": label,
                    "locations": [{"location": loc} for loc in locs],
                })
                related.extend(locs)
            result["codeFlows"] = [{"threadFlows": thread_flows}]
            result["relatedLocations"] = related
        results.append(result)
    return json.dumps({
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "rafiki-tpu-lint",
                "rules": [rule_meta[r] for r in sorted(rule_meta)],
            }},
            "results": results,
        }],
    }, indent=2)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps({
        "findings": [f.to_dict() for f in findings],
        "counts": {
            "total": len(findings),
            "error": sum(1 for f in findings if f.severity == "error"),
            "warning": sum(1 for f in findings
                           if f.severity == "warning"),
        },
    }, indent=2)
