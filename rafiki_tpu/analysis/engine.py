"""Rule framework + driver for ``rafiki-tpu lint``.

The engine is deliberately tiny: a rule is a class with an ``id``, a
``severity``, and a ``check(ctx)`` generator over one parsed module.
Everything stateful (source text, AST, parent links, suppression
comments) lives in :class:`ModuleContext`, built once per file and
shared by every rule — rules never re-read the file or re-parse.

Suppression follows the repo-wide comment dialect::

    risky_line()  # rafiki: noqa[silent-except]
    other_line()  # rafiki: noqa          (blanket — any rule)

A suppression must sit on the finding's own line (or the first line of
the multi-line statement that produced it); file-wide opt-outs are
intentionally not offered — they rot.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

#: suppression comment: ``# rafiki: noqa`` or ``# rafiki: noqa[a, b]``.
#: The lookahead rejects malformed forms (``noqa[rule`` without ``]``,
#: ``noqaX``) rather than silently widening them to a blanket
#: suppression of every rule on the line.
_NOQA_RE = re.compile(r"#\s*rafiki:\s*noqa(?:\[([^\]]*)\]|(?![\w\[-]))")

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic, pinned to a file location."""

    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.severity}] {self.rule}: {self.message}")

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class ModuleContext:
    """Everything a rule may inspect about one module, parsed once."""

    def __init__(self, source: str, path: str):
        self.source = source
        self.path = path
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        # parent links: rules constantly ask "am I inside X?"
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self._noqa = _collect_noqa(source)
        self._traced = None  # lazy; see traced()

    def traced(self):
        """The module's traced-function map
        (:func:`rafiki_tpu.analysis.astutil.traced_functions`),
        computed once and shared by every JAX rule."""
        if self._traced is None:
            from .astutil import traced_functions

            self._traced = traced_functions(self.tree)
        return self._traced

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(
            self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def suppressed(self, rule_id: str, line: int) -> bool:
        ids = self._noqa.get(line)
        if ids is None:
            return False
        return not ids or rule_id in ids


def _collect_noqa(source: str) -> Dict[int, frozenset]:
    """Map line number -> suppressed rule ids (empty set = blanket).

    Uses the tokenizer, not a per-line regex, so a ``# rafiki: noqa``
    inside a string literal is NOT a suppression.
    """
    out: Dict[int, frozenset] = {}
    try:
        tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _NOQA_RE.search(tok.string)
            if not m:
                continue
            ids = frozenset(
                part.strip() for part in (m.group(1) or "").split(",")
                if part.strip())
            out[tok.start[0]] = ids
    except tokenize.TokenError:
        pass  # unterminated string etc. — the parse error is reported
    return out


class Rule:
    """Base class for lint rules.

    Subclasses set ``id`` (kebab-case, stable — it is the suppression
    key), ``category`` (``jax`` | ``concurrency`` | ``robustness``),
    ``severity``, and a one-line ``description`` (shown by
    ``lint --list-rules`` and used in docs). ``check`` yields
    ``(node, message)`` pairs; the engine attaches location, severity,
    and suppression handling.
    """

    id: str = ""
    category: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, ctx: ModuleContext):  # pragma: no cover - interface
        raise NotImplementedError
        yield  # noqa: unreachable — marks this as a generator


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule to the global registry."""
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"rule {cls.id}: bad severity {cls.severity!r}")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls()
    return cls


def all_rules() -> Dict[str, Rule]:
    """The registry, loading the built-in rule modules on first use."""
    from . import rules  # noqa: F401 — import side effect registers

    return dict(_REGISTRY)


def get_rule(rule_id: str) -> Rule:
    rules = all_rules()
    if rule_id not in rules:
        raise KeyError(
            f"unknown rule {rule_id!r} (known: {', '.join(sorted(rules))})")
    return rules[rule_id]


def _resolve_rules(select: Optional[Sequence[str]]) -> List[Rule]:
    rules = all_rules()
    if select is None:
        return list(rules.values())
    return [get_rule(r) for r in select]


def analyze_source(source: str, path: str = "<string>",
                   select: Optional[Sequence[str]] = None,
                   with_suppressed: bool = False) -> List[Finding]:
    """Run rules over one module's source; returns sorted findings.

    ``with_suppressed`` keeps ``# rafiki: noqa``-silenced findings in
    the result (used by the suppression tests and ``--show-suppressed``).
    """
    try:
        ctx = ModuleContext(source, path)
    except SyntaxError as e:
        return [Finding("parse-error", "error", path, e.lineno or 1,
                        (e.offset or 1) - 1,
                        f"could not parse: {e.msg}")]
    findings: List[Finding] = []
    for rule in _resolve_rules(select):
        for node, message in rule.check(ctx):
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
            if not with_suppressed and ctx.suppressed(rule.id, line):
                continue
            findings.append(Finding(rule.id, rule.severity, path,
                                    line, col, message))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


#: directories never worth descending into when walking a tree
_SKIP_DIRS = {"__pycache__", ".git", ".eggs", "build", "dist",
              "node_modules"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        if not os.path.isdir(path):
            # fail loudly per-path: one typo'd argument must not make
            # the gate report "clean" on a tree it never visited
            raise OSError(f"no such file or directory: {path!r}")
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS
                             and not d.endswith(".egg-info"))
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def analyze_paths(paths: Iterable[str],
                  select: Optional[Sequence[str]] = None,
                  with_suppressed: bool = False) -> List[Finding]:
    """Run rules over files/trees; nonexistent paths raise ``OSError``."""
    findings: List[Finding] = []
    seen = False
    for path in iter_python_files(paths):
        seen = True
        with open(path, "rb") as f:
            raw = f.read()
        try:
            source = raw.decode("utf-8")
        except UnicodeDecodeError as e:
            # a finding, not a crash: the gate must report the file and
            # exit 1, not unwind with a traceback
            findings.append(Finding("parse-error", "error", path, 1, 0,
                                    f"not valid UTF-8: {e}"))
            continue
        findings.extend(analyze_source(source, path, select=select,
                                       with_suppressed=with_suppressed))
    if not seen:
        raise OSError(f"no python files under {list(paths)!r}")
    return findings


def render_text(findings: Sequence[Finding]) -> str:
    lines = [f.format() for f in findings]
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    lines.append(f"{len(findings)} finding(s): {n_err} error(s), "
                 f"{n_warn} warning(s)")
    return "\n".join(lines)


def render_sarif(findings: Sequence[Finding]) -> str:
    """SARIF 2.1.0 — the interchange format editors and CI annotation
    APIs (GitHub code scanning, VS Code SARIF viewer) consume.

    One run, one driver; every rule that produced a finding gets a
    ``rules`` entry with its description so viewers can show it inline.
    Paths are emitted relative to the working directory when possible —
    SARIF consumers resolve relative URIs against the repo root.
    """
    rule_meta: Dict[str, Dict[str, object]] = {}

    def _describe(rule_id: str) -> None:
        if rule_id in rule_meta:
            return
        from .project import all_project_rules

        rule = all_rules().get(rule_id) or \
            all_project_rules().get(rule_id)
        entry: Dict[str, object] = {"id": rule_id}
        if rule is not None:
            entry["shortDescription"] = {"text": rule.description}
            entry["properties"] = {"category": rule.category}
        rule_meta[rule_id] = entry

    results = []
    for f in findings:
        _describe(f.rule)
        path = f.path
        if os.path.isabs(path):
            try:
                path = os.path.relpath(path)
            except ValueError:  # different drive (windows) — keep abs
                pass
        results.append({
            "ruleId": f.rule,
            "level": f.severity,  # SARIF levels include error/warning
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": path.replace(os.sep, "/")},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
        })
    return json.dumps({
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "rafiki-tpu-lint",
                "rules": [rule_meta[r] for r in sorted(rule_meta)],
            }},
            "results": results,
        }],
    }, indent=2)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps({
        "findings": [f.to_dict() for f in findings],
        "counts": {
            "total": len(findings),
            "error": sum(1 for f in findings if f.severity == "error"),
            "warning": sum(1 for f in findings
                           if f.severity == "warning"),
        },
    }, indent=2)
