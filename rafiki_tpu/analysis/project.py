"""Whole-program analysis: :class:`ProjectContext` + :class:`ProjectRule`.

The per-module engine (:mod:`rafiki_tpu.analysis.engine`) sees one
``ModuleContext`` at a time, so the bug classes that actually cost
review passes — a hub decorator silently not wrapping four verbs, a
lock cycle spanning two classes, a metric registered in one layer and
documented (or dashboarded) in another — were not expressible as rules.
This module parses the whole package ONCE and hands every project rule
the same shared view:

- **module registry** — dotted module name -> the same ``ModuleContext``
  the per-file rules use (parsed once, shared);
- **import graph** — per module, local name -> fully qualified target,
  with relative imports resolved;
- **class/attribute resolution** — every class with its methods, its
  resolved project bases, and a light ``self.attr`` -> class type map
  (from ``self.x = ClassName(...)`` assignments);
- **light call graph** — per function, best-effort resolution of
  ``self.m()`` / ``helper()`` / ``self.attr.m()`` call sites to other
  project functions;
- **text resources** — the non-Python files cross-layer contracts live
  in (``kv_server.cc``, ``docs/*.md``, ``dashboard.html``), loaded as
  line lists so rules can diff code against them.

Suppression reuses the repo dialect: ``# rafiki: noqa[rule-id]`` on the
finding's line. For findings anchored in non-Python resources the same
token works inside that file's own comment syntax (``<!-- rafiki:
noqa[rule] -->`` in HTML/Markdown, ``// rafiki: noqa[rule]`` in C++) —
the engine just searches the finding's line for the token.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import (Dict, Iterator, List, Optional, Sequence, Set,
                    Tuple)

from .engine import (Finding, ModuleContext, _NOQA_RE, SEVERITIES,
                     iter_python_files, suppression_matches)

#: the suppression token inside non-Python comment syntaxes: C++
#: (``// rafiki: noqa[x]``), HTML/Markdown (``<!-- rafiki: noqa[x]
#: -->``), block comments. Same grammar as the Python dialect.
_RES_NOQA_RE = re.compile(
    r"(?:#|//|<!--|/\*)\s*rafiki:\s*noqa"
    r"(?:\[([^\]]*)\]|(?![\w\[-]))")

#: extra (non-``.py``) files worth loading as text resources: the other
#: halves of cross-layer contracts.
_RESOURCE_EXTS = (".cc", ".cpp", ".h", ".md", ".html")


@dataclasses.dataclass
class ClassInfo:
    """One class, resolved against the project."""

    module: str
    name: str
    node: ast.ClassDef
    #: base spellings resolved to project-qualified ``module:Class``
    #: where possible (unresolved externals keep their dotted spelling)
    bases: List[str]
    methods: Dict[str, ast.AST]
    #: ``attr`` -> ``module:Class`` for ``self.attr = ClassName(...)``
    attr_types: Dict[str, str]

    @property
    def qualname(self) -> str:
        return f"{self.module}:{self.name}"


@dataclasses.dataclass
class FunctionInfo:
    """One function or method with its light call-site resolution."""

    module: str
    #: ``Class.method`` or bare ``name``
    name: str
    node: ast.AST
    cls: Optional[str]  # owning class qualname, if a method

    @property
    def qualname(self) -> str:
        return f"{self.module}:{self.name}"


class TextResource:
    """A non-Python file a contract lives in (docs, C++, dashboard)."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()

    def find_line(self, needle: str, start: int = 1) -> int:
        """1-based line of the first occurrence of ``needle`` at or
        after ``start`` (0 when absent) — for anchoring findings."""
        for i in range(start - 1, len(self.lines)):
            if needle in self.lines[i]:
                return i + 1
        return 0


def _module_name(path: str, root: str) -> str:
    rel = os.path.relpath(path, root)
    parts = rel[:-3].split(os.sep)  # strip .py
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    base = os.path.basename(os.path.abspath(root))
    return ".".join([base] + parts) if parts else base


class ProjectContext:
    """Everything a :class:`ProjectRule` may inspect, parsed once."""

    def __init__(self, roots: Sequence[str]):
        self.roots = [os.path.abspath(r) for r in roots]
        self.modules: Dict[str, ModuleContext] = {}
        self.module_infos: Dict[str, Tuple[str, str]] = {}  # name->(path,root)
        self.parse_errors: List[Finding] = []
        self.resources: Dict[str, TextResource] = {}  # basename -> res
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        #: per module: local name -> fully qualified project target
        self.imports: Dict[str, Dict[str, str]] = {}
        self._noqa_cache: Dict[str, Dict[int, frozenset]] = {}
        #: scratch cache for rule-computed whole-program facts (the
        #: thread model + access summaries all three race rules share)
        #: — keyed by the computing module's name
        self.memo: Dict[str, object] = {}
        self._load()
        self._index()

    # ---- loading ----

    def _load(self) -> None:
        for root in self.roots:
            root_dir = root if os.path.isdir(root) else os.path.dirname(root)
            for path in iter_python_files([root]):
                with open(path, "rb") as f:
                    raw = f.read()
                try:
                    source = raw.decode("utf-8")
                    ctx = ModuleContext(source, path)
                except (UnicodeDecodeError, SyntaxError) as e:
                    line = getattr(e, "lineno", 1) or 1
                    self.parse_errors.append(Finding(
                        "parse-error", "error", path, line, 0,
                        f"could not parse: {e}"))
                    continue
                name = _module_name(path, root_dir)
                self.modules[name] = ctx
                self.module_infos[name] = (path, root_dir)
            self._load_resources(root_dir)
            # docs/ conventionally sits NEXT to the package dir (repo
            # root) — include it so doc-parity rules see the catalog
            sibling_docs = os.path.join(os.path.dirname(root_dir), "docs")
            if os.path.isdir(sibling_docs):
                self._load_resources(sibling_docs)

    def _load_resources(self, root_dir: str) -> None:
        for cur, dirs, files in os.walk(root_dir):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git", "build",
                                          "dist", "node_modules"))
            for fname in sorted(files):
                if not fname.endswith(_RESOURCE_EXTS):
                    continue
                path = os.path.join(cur, fname)
                try:
                    with open(path, "r", encoding="utf-8",
                              errors="replace") as f:
                        text = f.read()
                except OSError:
                    continue
                # first one wins per basename: rules address resources
                # by filename (``kv_server.cc``), and fixtures mirror
                # the real layout
                self.resources.setdefault(fname, TextResource(path, text))

    def resource(self, basename: str) -> Optional[TextResource]:
        return self.resources.get(basename)

    def md_resources(self) -> List[TextResource]:
        return [r for n, r in sorted(self.resources.items())
                if n.endswith(".md")]

    # ---- indexing ----

    def _index(self) -> None:
        # pass 1: class + function defs, import tables
        for mod, ctx in self.modules.items():
            self.imports[mod] = self._import_table(mod, ctx.tree)
            for node in ctx.tree.body:
                self._index_top(mod, node)
        # pass 2: resolve bases + attr types against the global table
        self._short = {}  # bare class name -> qualnames (ambiguity-aware)
        for q, info in self.classes.items():
            self._short.setdefault(info.name, []).append(q)
        for info in self.classes.values():
            info.bases = [self.resolve_class(info.module, b) or b
                          for b in info.bases]
            for attr, spelling in list(info.attr_types.items()):
                q = self.resolve_class(info.module, spelling)
                if q:
                    info.attr_types[attr] = q
                else:
                    del info.attr_types[attr]

    def _index_top(self, mod: str, node: ast.AST,
                   depth: int = 0) -> None:
        if isinstance(node, ast.ClassDef):
            self._index_class(mod, node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = FunctionInfo(mod, node.name, node, None)
            self.functions.setdefault(fi.qualname, fi)
        elif isinstance(node, (ast.If, ast.Try)) and depth < 2:
            for child in ast.iter_child_nodes(node):
                self._index_top(mod, child, depth + 1)

    def _index_class(self, mod: str, cls: ast.ClassDef) -> None:
        from .astutil import dotted

        methods: Dict[str, ast.AST] = {}
        attr_types: Dict[str, str] = {}
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[item.name] = item
        # self.x = ClassName(...) anywhere in the class body types
        # the attribute (last assignment wins — fine for lint)
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if not (isinstance(node.value, ast.Call)):
                continue
            ctor = dotted(node.value.func)
            if not ctor:
                continue
            for t in node.targets:
                path = dotted(t)
                if path and path.startswith("self.") and \
                        path.count(".") == 1:
                    attr_types[path[5:]] = ctor
        info = ClassInfo(mod, cls.name, cls,
                         [b for b in (dotted(b) for b in cls.bases)
                          if b], methods, attr_types)
        self.classes[info.qualname] = info
        for name, m in methods.items():
            fi = FunctionInfo(mod, f"{cls.name}.{name}", m,
                              info.qualname)
            self.functions.setdefault(fi.qualname, fi)

    def _import_table(self, mod: str,
                      tree: ast.Module) -> Dict[str, str]:
        """Local name -> dotted target, with relative imports resolved
        against this module's package."""
        table: Dict[str, str] = {}
        pkg_parts = mod.split(".")
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    table[alias.asname or
                          alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    # relative: level 1 = this package
                    base_parts = pkg_parts[:-(node.level)] \
                        if len(pkg_parts) >= node.level else []
                    base = ".".join(base_parts + (
                        [node.module] if node.module else []))
                else:
                    base = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    table[alias.asname or alias.name] = \
                        f"{base}.{alias.name}" if base else alias.name
        return table

    # ---- resolution helpers ----

    def resolve_class(self, mod: str, spelling: str) -> Optional[str]:
        """A class spelling as seen from ``mod`` -> project qualname."""
        if spelling in self.classes:
            return spelling
        head, _, rest = spelling.partition(".")
        target = self.imports.get(mod, {}).get(head)
        if target:
            full = f"{target}.{rest}" if rest else target
            # full is module.path.Class — split at the last dot
            m, _, c = full.rpartition(".")
            if m in self.modules and f"{m}:{c}" in self.classes:
                return f"{m}:{c}"
        # same module?
        if not rest and f"{mod}:{head}" in self.classes:
            return f"{mod}:{head}"
        # unique bare name anywhere in the project (light but right
        # far more often than not inside one package)
        cands = getattr(self, "_short", {}).get(
            spelling.rsplit(".", 1)[-1], [])
        if len(cands) == 1:
            return cands[0]
        return None

    def class_mro(self, qualname: str) -> List[ClassInfo]:
        """The project-resolvable part of a class's MRO (itself first);
        cycles and externals are skipped."""
        out: List[ClassInfo] = []
        seen: Set[str] = set()
        stack = [qualname]
        while stack:
            q = stack.pop(0)
            if q in seen or q not in self.classes:
                continue
            seen.add(q)
            info = self.classes[q]
            out.append(info)
            stack.extend(b for b in info.bases if b in self.classes)
        return out

    def resolve_call(self, caller: FunctionInfo,
                     call: ast.Call) -> Optional[FunctionInfo]:
        """Best-effort project target of one call site."""
        from .astutil import dotted

        name = dotted(call.func)
        if not name:
            return None
        parts = name.split(".")
        mod = caller.module
        # self.m() / self.attr.m()
        if parts[0] == "self" and caller.cls:
            if len(parts) == 2:
                return self._method(caller.cls, parts[1])
            if len(parts) == 3:
                info = self.classes.get(caller.cls)
                for c in self.class_mro(caller.cls):
                    t = c.attr_types.get(parts[1])
                    if t:
                        return self._method(t, parts[2])
                return None
            return None
        # bare helper()
        if len(parts) == 1:
            q = f"{mod}:{parts[0]}"
            if q in self.functions:
                return self.functions[q]
            target = self.imports.get(mod, {}).get(parts[0])
            if target:
                m, _, f = target.rpartition(".")
                if f"{m}:{f}" in self.functions:
                    return self.functions[f"{m}:{f}"]
            return None
        # imported_module.func() or ImportedClass.method()
        target = self.imports.get(mod, {}).get(parts[0])
        if target and len(parts) == 2:
            if f"{target}:{parts[1]}" in self.functions:
                return self.functions[f"{target}:{parts[1]}"]
            cq = self.resolve_class(mod, parts[0])
            if cq:
                return self._method(cq, parts[1])
        return None

    def _method(self, cls_q: str, name: str) -> Optional[FunctionInfo]:
        for c in self.class_mro(cls_q):
            fi = self.functions.get(f"{c.module}:{c.name}.{name}")
            if fi is not None:
                return fi
        return None

    # ---- suppression ----

    def suppressed(self, rule_id: str, path: str, line: int) -> bool:
        for ctx in self.modules.values():
            if ctx.path == path:
                return ctx.suppressed(rule_id, line)
        # non-Python resource: search the line itself for the token
        noqa = self._noqa_cache.get(path)
        if noqa is None:
            noqa = {}
            res = next((r for r in self.resources.values()
                        if r.path == path), None)
            if res is not None:
                for i, text in enumerate(res.lines):
                    m = _RES_NOQA_RE.search(text)
                    if m:
                        noqa[i + 1] = frozenset(
                            p.strip()
                            for p in (m.group(1) or "").split(",")
                            if p.strip())
            self._noqa_cache[path] = noqa
        ids = noqa.get(line)
        if ids is None:
            return False
        return suppression_matches(rule_id, ids)


class ProjectRule:
    """Base class for whole-program rules.

    Like :class:`~rafiki_tpu.analysis.engine.Rule` but ``check`` takes
    the :class:`ProjectContext` and yields ``(path, line, col,
    message)`` tuples — project findings may anchor in ANY file the
    contract touches (a Python module, ``docs/observability.md``,
    ``kv_server.cc``), so rules name locations explicitly. The helper
    :meth:`at` converts a ``(ModuleContext, ast-node)`` pair.

    Rules may append a fifth element: ``threads``, a tuple of
    ``(label, trace-steps)`` pairs carried onto the finding — the
    concurrency layer uses it to render one stack per thread context.
    ``layer`` distinguishes the sub-registries ``--list-rules`` tags:
    plain cross-layer contracts are ``"project"``, the thread-model
    rules (:mod:`.rules.project_threads`) are ``"threads"``.
    """

    id: str = ""
    category: str = "project"
    severity: str = "error"
    description: str = ""
    layer: str = "project"

    def check(self, project: ProjectContext
              ) -> Iterator[Tuple[str, int, int, str]]:
        raise NotImplementedError  # pragma: no cover - interface
        yield

    @staticmethod
    def at(ctx: ModuleContext, node: ast.AST, message: str
           ) -> Tuple[str, int, int, str]:
        return (ctx.path, getattr(node, "lineno", 1),
                getattr(node, "col_offset", 0), message)


_PROJECT_REGISTRY: Dict[str, ProjectRule] = {}


def register_project(cls):
    """Class decorator adding a project rule to the registry."""
    if not cls.id:
        raise ValueError(f"project rule {cls.__name__} has no id")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"rule {cls.id}: bad severity {cls.severity!r}")
    if cls.id in _PROJECT_REGISTRY:
        raise ValueError(f"duplicate project rule id {cls.id!r}")
    _PROJECT_REGISTRY[cls.id] = cls()
    return cls


def all_project_rules() -> Dict[str, ProjectRule]:
    from . import rules  # noqa: F401 — import side effect registers

    return dict(_PROJECT_REGISTRY)


def get_project_rule(rule_id: str) -> ProjectRule:
    rules = all_project_rules()
    if rule_id not in rules:
        raise KeyError(f"unknown project rule {rule_id!r} "
                       f"(known: {', '.join(sorted(rules))})")
    return rules[rule_id]


def analyze_project(paths: Sequence[str],
                    select: Optional[Sequence[str]] = None,
                    with_suppressed: bool = False) -> List[Finding]:
    """Run project rules over the whole tree; sorted findings.

    ``select`` filters to the named project rules (unknown ids raise
    ``KeyError`` like the per-module engine). Parse failures surface as
    ``parse-error`` findings — a module the project pass cannot see is
    itself a finding, not a silent shrink of the analyzed surface.
    """
    rules = all_project_rules()
    if select is not None:
        chosen = [get_project_rule(r) for r in select
                  if r in rules]
    else:
        chosen = list(rules.values())
    project = ProjectContext(paths)
    findings: List[Finding] = list(project.parse_errors)
    for rule in chosen:
        for item in rule.check(project):
            path, line, col, message = item[:4]
            threads = tuple(item[4]) if len(item) > 4 else ()
            if not with_suppressed and \
                    project.suppressed(rule.id, path, line):
                continue
            findings.append(Finding(rule.id, rule.severity, path,
                                    line, col, message, (), threads))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


#: shared shape for "does this look like a metric/identifier name"
NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")
