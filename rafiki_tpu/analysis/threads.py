"""Thread model: root discovery + interprocedural context reachability.

The per-module concurrency rules retired in PR 18 could see *locks*
but not *threads*: a field locked in ``serving/`` and written bare
from a loop spawned in ``worker/`` looked fine to both files. This
module gives the project pass the missing half — *which threads
actually run which code*:

1. **Root discovery** — every way this codebase starts concurrent
   execution: ``threading.Thread(target=...)`` / ``threading.Timer``
   (including the dominant nested-``def loop()`` idiom and
   ``Thread(target=w.run)`` through a locally constructed object),
   ``executor.submit(fn, ...)``, and ``svc.route(method, pattern,
   handler)`` HTTP handler registrations (``JsonHttpService`` /
   ``ObsServer`` dispatch handlers on per-connection server threads).
2. **Reachability** — a BFS per root over the ProjectContext call
   graph, so every function carries the set of thread contexts it can
   run under. The ``main`` pseudo-context seeds from every function
   with no resolved project caller that is not itself a thread target
   (public API, CLI entry points, test surface) and propagates
   forward like any other context.
3. **Witness traces** — BFS parent pointers reconstruct, for any
   (context, function) pair, the spawn-site → call-chain stack the
   race renderer shows as one SARIF ``threadFlow``.

Targets we cannot resolve to a project function (``functools.partial``
wrappers, stdlib callables like ``server.serve_forever``) contribute
no root — the handlers those servers dispatch to are discovered
through ``.route`` instead, which is where the shared state actually
gets touched.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .astutil import dotted
from .engine import TraceStep
from .project import FunctionInfo, ProjectContext

#: the pseudo-context for code reachable without any spawn: whatever
#: thread constructed the object / called the public API
MAIN = "main"

#: methods that start threads when named as ``<obj>.<method>`` — the
#: executor-submit form (one task per call, arbitrarily many in flight)
_SUBMIT_ATTRS = {"submit"}

#: constructor/teardown methods whose writes happen before the object
#: is shared (or after it stops being) — the seed of the setup closure
SETUP_METHODS = {"__init__", "__new__", "__enter__", "__post_init__"}


@dataclasses.dataclass(frozen=True)
class ThreadRoot:
    """One discovered way the project starts concurrent execution."""

    kind: str            # "thread" | "timer" | "executor" | "handler"
    name: str            # display name (name= kwarg, route, or target)
    target: str          # qualname of the entry function
    path: str            # file of the spawn site
    line: int
    col: int
    daemon: bool
    spawner: Optional[str]   # qualname of the spawning function
    multi: bool          # >1 instance may run concurrently
    #: first line at which the thread can actually be running — the
    #: ``.start()`` call when we find one, else the spawn expression.
    #: Writes in the spawner before this line happen-before the root.
    start_line: int = 0

    @property
    def label(self) -> str:
        return f"{self.kind}:{self.name}"


def walk_own(fn_node: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` minus nested function/class bodies: what THIS
    function executes when called, not what its closures do later."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class ThreadModel:
    """Roots + per-function thread contexts for one project."""

    def __init__(self, project: ProjectContext):
        self.project = project
        #: project functions plus synthetic entries for nested-def
        #: thread targets (``mod:Cls.meth.<locals>.loop``)
        self.functions: Dict[str, FunctionInfo] = dict(project.functions)
        self.roots: List[ThreadRoot] = []
        self._discover()
        #: caller qualname -> {callee qualname: representative call}
        self._adj: Dict[str, Dict[str, ast.Call]] = {}
        self._build_adjacency()
        #: context label -> set of reachable function qualnames
        self.reach: Dict[str, Set[str]] = {}
        #: (label, qualname) -> (caller qualname, call node)
        self._parent: Dict[Tuple[str, str], Tuple[str, ast.Call]] = {}
        self._roots_by_label: Dict[str, ThreadRoot] = {}
        self._compute_reachability()
        self._setup_cache: Dict[str, Set[str]] = {}

    # ---- discovery ----

    def _discover(self) -> None:
        for mod, ctx in sorted(self.project.modules.items()):
            node_to_fi = {id(fi.node): fi
                          for fi in self.project.functions.values()
                          if fi.module == mod}
            for call in ast.walk(ctx.tree):
                if not isinstance(call, ast.Call):
                    continue
                spec = self._classify(call)
                if spec is None:
                    continue
                kind, target_expr, name = spec
                fi = self._enclosing(ctx, call, node_to_fi)
                target = self._resolve_target(mod, fi, target_expr)
                if target is None:
                    continue
                in_loop = any(isinstance(a, (ast.For, ast.While,
                                             ast.AsyncFor))
                              for a in ctx.ancestors(call))
                daemon = self._daemon(call, fi)
                if not name:
                    name = target.rsplit(":", 1)[-1].rsplit(".", 1)[-1]
                self.roots.append(ThreadRoot(
                    kind=kind, name=name, target=target,
                    path=ctx.path, line=call.lineno,
                    col=call.col_offset, daemon=daemon,
                    spawner=fi.qualname if fi else None,
                    multi=in_loop or kind in ("executor", "handler"),
                    start_line=self._start_line(call, fi)))

    @staticmethod
    def _classify(call: ast.Call):
        """(kind, target expression, display name) or None."""
        fname = dotted(call.func) or ""
        last = fname.rsplit(".", 1)[-1]
        kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        if last == "Thread":
            target = kwargs.get("target")
            return ("thread", target, _const_str(kwargs.get("name"))) \
                if target is not None else None
        if last == "Timer":
            # threading.Timer(interval, function)
            target = kwargs.get("function") or (
                call.args[1] if len(call.args) > 1 else None)
            return ("timer", target, None) \
                if target is not None else None
        if isinstance(call.func, ast.Attribute):
            if last in _SUBMIT_ATTRS and call.args:
                return ("executor", call.args[0], None)
            if last == "route" and len(call.args) >= 3:
                # svc.route(method, pattern, handler): the handler
                # runs on the HTTP server's per-connection threads
                return ("handler", call.args[2],
                        _const_str(call.args[1]))
        return None

    @staticmethod
    def _enclosing(ctx, node: ast.AST,
                   node_to_fi) -> Optional[FunctionInfo]:
        """The innermost *indexed* function containing ``node`` (a
        spawn inside a nested def charges the enclosing method)."""
        for anc in ctx.ancestors(node):
            fi = node_to_fi.get(id(anc))
            if fi is not None:
                return fi
        return None

    def _resolve_target(self, mod: str, fi: Optional[FunctionInfo],
                        expr: ast.AST) -> Optional[str]:
        """Target expression -> qualname of the entry function."""
        path = dotted(expr)
        if not path:
            return None
        segs = path.split(".")
        project = self.project
        if segs[0] == "self" and fi is not None and fi.cls:
            if len(segs) == 2:
                m = project._method(fi.cls, segs[1])
                return m.qualname if m else None
            if len(segs) == 3:
                for c in project.class_mro(fi.cls):
                    t = c.attr_types.get(segs[1])
                    if t:
                        m = project._method(t, segs[2])
                        return m.qualname if m else None
            return None
        if len(segs) == 1:
            name = segs[0]
            # the dominant idiom: a nested ``def loop():`` in the
            # spawning function — promote it to a synthetic entry
            if fi is not None:
                for node in ast.walk(fi.node):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) and \
                            node.name == name and node is not fi.node:
                        syn = FunctionInfo(
                            fi.module,
                            f"{fi.name}.<locals>.{name}", node, fi.cls)
                        self.functions.setdefault(syn.qualname, syn)
                        return syn.qualname
            if f"{mod}:{name}" in self.functions:
                return f"{mod}:{name}"
            imp = project.imports.get(mod, {}).get(name)
            if imp:
                m, _, f = imp.rpartition(".")
                if f"{m}:{f}" in self.functions:
                    return f"{m}:{f}"
            return None
        if len(segs) == 2:
            # w = Worker(...); Thread(target=w.run)
            if fi is not None:
                for node in walk_own(fi.node):
                    if isinstance(node, ast.Assign) and \
                            isinstance(node.value, ast.Call) and \
                            any(isinstance(t, ast.Name) and
                                t.id == segs[0]
                                for t in node.targets):
                        ctor = dotted(node.value.func)
                        cq = ctor and project.resolve_class(mod, ctor)
                        if cq:
                            m = project._method(cq, segs[1])
                            return m.qualname if m else None
            imp = project.imports.get(mod, {}).get(segs[0])
            if imp:
                if f"{imp}:{segs[1]}" in self.functions:
                    return f"{imp}:{segs[1]}"
                cq = project.resolve_class(mod, segs[0])
                if cq:
                    m = project._method(cq, segs[1])
                    return m.qualname if m else None
        return None

    @staticmethod
    def _daemon(call: ast.Call, fi: Optional[FunctionInfo]) -> bool:
        for kw in call.keywords:
            if kw.arg == "daemon":
                return isinstance(kw.value, ast.Constant) and \
                    bool(kw.value.value)
        if fi is not None:
            # t.daemon = True after construction, same function
            for node in walk_own(fi.node):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Attribute) and
                        t.attr == "daemon"
                        for t in node.targets):
                    v = node.value
                    return isinstance(v, ast.Constant) and bool(v.value)
        return False

    @staticmethod
    def _start_line(call: ast.Call,
                    fi: Optional[FunctionInfo]) -> int:
        """Line of the matching ``.start()`` (first one at or after
        the spawn expression) — the happens-before frontier."""
        best = 0
        if fi is not None:
            for node in walk_own(fi.node):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "start" and \
                        node.lineno >= call.lineno:
                    if best == 0 or node.lineno < best:
                        best = node.lineno
        return best or call.lineno

    # ---- call graph + reachability ----

    def _build_adjacency(self) -> None:
        project = self.project
        for q, fi in self.functions.items():
            edges: Dict[str, ast.Call] = {}
            for node in walk_own(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                target = project.resolve_call(fi, node)
                if target is not None and \
                        target.qualname in self.functions:
                    edges.setdefault(target.qualname, node)
            self._adj[q] = edges

    def _bfs(self, label: str, seeds: List[str]) -> Set[str]:
        seen: Set[str] = set()
        frontier = [q for q in seeds if q in self.functions]
        seen.update(frontier)
        while frontier:
            nxt: List[str] = []
            for q in frontier:
                for callee, call in sorted(
                        self._adj.get(q, {}).items()):
                    if callee in seen:
                        continue
                    seen.add(callee)
                    self._parent[(label, callee)] = (q, call)
                    nxt.append(callee)
            frontier = nxt
        return seen

    def _compute_reachability(self) -> None:
        labels: Dict[str, int] = {}
        for root in self.roots:
            # labels must be unique — two services both routing
            # "/health" are distinct contexts
            base = root.label
            n = labels.get(base, 0)
            labels[base] = n + 1
            label = base if n == 0 else f"{base}#{n + 1}"
            self._roots_by_label[label] = root
            self.reach[label] = self._bfs(label, [root.target])
        targets = {r.target for r in self.roots}
        called: Set[str] = set()
        for edges in self._adj.values():
            called.update(edges)
        seeds = sorted(q for q in self.functions
                       if q not in targets and q not in called)
        self.reach[MAIN] = self._bfs(MAIN, seeds)

    # ---- queries ----

    def contexts_of(self, qualname: str) -> frozenset:
        return frozenset(label for label, reach in self.reach.items()
                         if qualname in reach)

    def root_of(self, label: str) -> Optional[ThreadRoot]:
        return self._roots_by_label.get(label)

    def is_multi(self, label: str) -> bool:
        root = self._roots_by_label.get(label)
        return root.multi if root is not None else False

    def module_path(self, qualname: str) -> str:
        fi = self.functions.get(qualname)
        if fi is None:
            return ""
        ctx = self.project.modules.get(fi.module)
        return ctx.path if ctx is not None else ""

    def trace(self, label: str, qualname: str) -> Tuple[TraceStep, ...]:
        """Spawn-site → call-chain stack placing ``qualname`` under
        context ``label`` (empty when it is not reachable there)."""
        if qualname not in self.reach.get(label, ()):
            return ()
        hops: List[TraceStep] = []
        cur = qualname
        while True:
            parent = self._parent.get((label, cur))
            if parent is None:
                break
            caller, call = parent
            hops.append(TraceStep(
                call.lineno, call.col_offset,
                f"'{_short(caller)}' calls '{_short(cur)}'",
                self.module_path(caller)))
            cur = caller
        hops.reverse()
        root = self._roots_by_label.get(label)
        if root is not None:
            spawned = (f"in '{_short(root.spawner)}'"
                       if root.spawner else "at module scope")
            head = TraceStep(
                root.line, root.col,
                f"{root.kind} [{label}] spawned {spawned}, running "
                f"'{_short(root.target)}'", root.path)
            return (head,) + tuple(hops)
        entry = TraceStep(
            getattr(self.functions[cur].node, "lineno", 1),
            getattr(self.functions[cur].node, "col_offset", 0),
            f"'{_short(cur)}' runs on the caller's thread [main]",
            self.module_path(cur))
        return (entry,) + tuple(hops)

    # ---- happens-before ----

    def setup_closure(self, cls_q: str) -> Set[str]:
        """Method names of ``cls_q`` only reachable from construction
        (``__init__`` etc. plus helpers all of whose in-class callers
        are themselves setup) — the object is not shared with other
        threads while they run."""
        if cls_q in self._setup_cache:
            return self._setup_cache[cls_q]
        info = self.project.classes.get(cls_q)
        methods = dict(info.methods) if info else {}
        callers: Dict[str, Set[str]] = {n: set() for n in methods}
        for name, node in methods.items():
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id == "self" and \
                        sub.func.attr in callers:
                    callers[sub.func.attr].add(name)
        setup = set(SETUP_METHODS)
        changed = True
        while changed:
            changed = False
            for name in set(methods) - setup:
                if callers[name] and callers[name] <= setup:
                    setup.add(name)
                    changed = True
        self._setup_cache[cls_q] = setup
        return setup

    def happens_before(self, access_func: str, access_line: int,
                       other_label: str) -> bool:
        """Init-before-``start()`` exemption: does an access in
        ``access_func`` at ``access_line`` happen-before the root
        behind ``other_label`` even starts?

        Two orderings qualify. Inside the spawning function itself,
        anything before the ``.start()`` line runs before the thread
        exists. And a write in a class's setup closure (``__init__``
        and helpers only construction reaches) completes before the
        object is shared with ANY thread — except a root the same
        setup closure itself started (``self`` escaped mid-
        construction), which runs concurrently with the rest of it.
        """
        root = self._roots_by_label.get(other_label)
        if root is None:
            return False
        if access_func == root.spawner:
            return access_line < root.start_line
        fi = self.functions.get(access_func)
        if fi is None or fi.cls is None:
            return False
        setup = self.setup_closure(fi.cls)
        if _method_name(fi) not in setup:
            return False
        sp = self.functions.get(root.spawner) if root.spawner else None
        if sp is not None and sp.cls == fi.cls and \
                _method_name(sp) in setup:
            return False  # self escaped during construction
        return True


def _method_name(fi: FunctionInfo) -> str:
    return fi.name.rsplit(".", 1)[-1] if "." in fi.name else fi.name


def _short(qualname: Optional[str]) -> str:
    """``pkg.mod:Cls.meth`` -> ``Cls.meth`` for messages."""
    return qualname.rsplit(":", 1)[-1] if qualname else "?"


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
