"""Command-line front end for the lint engine.

Three consumers share this module: ``rafiki-tpu lint`` (the subcommand
in :mod:`rafiki_tpu.cli`), the ``rafiki-tpu-lint`` console entry
(pyproject), and ``scripts/lint.py`` (repo checkout, no install). All
of them parse the same flags and exit with the same contract:

- 0 — no unsuppressed findings (the CI gate passes)
- 1 — findings (printed to stdout; text, ``--format json``, or
  ``--format sarif``)
- 2 — usage/IO error (bad rule id, unreadable path, git failure)

Two scopes compose:

- per-module rules always run over the requested paths (narrowed to
  ``git diff`` output under ``--changed-only``);
- ``--project`` additionally runs the whole-program rules
  (:mod:`rafiki_tpu.analysis.project`) over the same roots — ALWAYS
  whole-tree, even under ``--changed-only``, because cross-layer
  contracts (hub verb parity, lock ordering) can be broken by the
  files you did NOT touch.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional, Tuple

from .dataflow import all_flow_rules
from .engine import (all_rules, analyze_paths, analyze_source,
                     render_json, render_sarif, render_text)
from .project import all_project_rules, analyze_project


def add_lint_arguments(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "paths", nargs="*", default=["rafiki_tpu"],
        help="files or directories to analyze (default: rafiki_tpu)")
    parser.add_argument(
        "--format", choices=["text", "json", "sarif"], default="text",
        help="finding output format")
    parser.add_argument(
        "--select", default=None, metavar="RULE[,RULE...]",
        help="run only these rule ids (default: all registered rules, "
             "per-module and project alike)")
    parser.add_argument(
        "--project", action="store_true",
        help="also run the whole-program rules (lock-order-cycle, "
             "hub-verb-parity, ...) over the full tree — the repo "
             "self-check runs with this on")
    parser.add_argument(
        "--changed-only", nargs="?", const="HEAD", default=None,
        metavar="BASE_REF",
        help="scope per-module rules to files changed vs BASE_REF "
             "(default HEAD: staged+worktree changes) plus untracked "
             "files; project rules still see the whole tree")
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="include findings silenced by `# rafiki: noqa[...]` "
             "comments (they then count toward the exit code — an "
             "audit mode, not the CI gate)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the registered rules and exit (flow rules are "
             "tagged [flow:...], project rules [project:...], "
             "thread-model rules [threads:...])")
    parser.add_argument(
        "--explain", default=None, metavar="RULE",
        help="print one rule's full story — description, and for "
             "flow rules the declared sources/sinks/sanitizers plus "
             "an example with its witness trace — and exit")


def _changed_files(base_ref: str) -> List[str]:
    """Paths changed vs ``base_ref`` plus untracked files, absolute.

    Raises ``OSError`` (-> exit 2) when git is unusable: a typo'd ref
    must not silently lint nothing and report clean.
    """
    out: List[str] = []
    for cmd in (["git", "diff", "--name-only", base_ref, "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise OSError(
                f"--changed-only: {' '.join(cmd)} failed: "
                f"{proc.stderr.strip()}")
        out.extend(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         capture_output=True, text=True)
    root = top.stdout.strip() if top.returncode == 0 else os.getcwd()
    return [os.path.join(root, p) for p in out]


def _scope_to_changed(paths: List[str],
                      changed: List[str]) -> List[str]:
    """Changed ``.py`` files that fall under the requested paths."""
    roots = [os.path.abspath(p) for p in paths]
    keep = []
    for path in changed:
        if not path.endswith(".py") or not os.path.exists(path):
            continue  # deleted files have no content to lint
        ap = os.path.abspath(path)
        if any(ap == r or ap.startswith(r + os.sep) for r in roots):
            keep.append(ap)
    return keep


def _split_select(select_arg: Optional[str]
                  ) -> Tuple[Optional[List[str]], Optional[List[str]]]:
    """``--select`` string -> (per-module ids, project ids).

    Unknown ids raise ``KeyError`` so the caller can exit 2.
    """
    if not select_arg:
        return None, None
    ids = [r.strip() for r in select_arg.split(",") if r.strip()]
    module_rules, project_rules = all_rules(), all_project_rules()
    flow_rules = all_flow_rules()
    known = set(module_rules) | set(project_rules) | set(flow_rules)
    for rule_id in ids:
        if rule_id not in known:
            raise KeyError(
                f"unknown rule {rule_id!r} "
                f"(known: {', '.join(sorted(known))})")
    # flow rules run in the per-file pass alongside module rules —
    # that is what makes --changed-only scope them for free
    return ([r for r in ids
             if r in module_rules or r in flow_rules],
            [r for r in ids if r in project_rules])


def _explain(rule_id: str) -> int:
    """Print one rule's full story; exit 0, or 2 on an unknown id."""
    module_rules, project_rules = all_rules(), all_project_rules()
    flow_rules = all_flow_rules()
    is_project = False
    if rule_id in flow_rules:
        rule, tag = flow_rules[rule_id], "flow"
    elif rule_id in module_rules:
        rule, tag = module_rules[rule_id], "module"
    elif rule_id in project_rules:
        rule = project_rules[rule_id]
        tag = getattr(rule, "layer", "project")
        is_project = True
    else:
        known = set(module_rules) | set(project_rules) | set(flow_rules)
        print(f"rafiki-tpu lint: unknown rule {rule_id!r} "
              f"(known: {', '.join(sorted(known))})", file=sys.stderr)
        return 2
    print(f"{rule_id} [{tag}:{rule.category}/{rule.severity}]")
    print(f"    {rule.description}")
    for heading, lines in (("sources", getattr(rule, "sources", ())),
                           ("sinks", getattr(rule, "sinks", ())),
                           ("sanitizers",
                            getattr(rule, "sanitizers", ()))):
        if lines:
            print(f"  {heading}:")
            for line in lines:
                print(f"    - {line}")
    example = getattr(rule, "example", "")
    if example:
        print("  example:")
        for line in example.rstrip("\n").splitlines():
            print(f"    | {line}")
        if is_project:
            findings = _explain_project_example(rule_id, rule, example)
        else:
            findings = analyze_source(example, path="<example>",
                                      select=[rule_id])
        if findings:
            print("  which the rule reports as:")
            for line in findings[0].format().splitlines():
                print(f"    {line}")
    return 0


def _explain_project_example(rule_id: str, rule, example: str):
    """Lint a project rule's example as a one-module mini-project;
    for thread-layer rules, first print the thread model the example
    discovers — the roots are half the story of a race finding."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        pkg = os.path.join(td, "example")
        os.makedirs(pkg)
        with open(os.path.join(pkg, "app.py"), "w") as f:
            f.write(example)
        if getattr(rule, "layer", "") == "threads":
            from .project import ProjectContext
            from .threads import ThreadModel

            model = ThreadModel(ProjectContext([pkg]))
            if model.roots:
                print("  thread model:")
                for root in model.roots:
                    extra = " multi-instance" if root.multi else ""
                    extra += " daemon" if root.daemon else ""
                    print(f"    - [{root.label}] runs "
                          f"'{root.target.rsplit(':', 1)[-1]}', "
                          f"spawned at line {root.line}"
                          f"{extra}")
        findings = analyze_project([pkg], select=[rule_id])
    # strip the tempdir from rendered paths so the output is stable
    return [f.__class__(f.rule, f.severity,
                        os.path.basename(f.path), f.line, f.col,
                        f.message, f.trace, tuple(
                            (label, tuple(
                                s.__class__(s.line, s.col, s.note,
                                            os.path.basename(s.path)
                                            if s.path else "")
                                for s in steps))
                            for label, steps in f.threads))
            for f in findings]


def run_lint(args: argparse.Namespace) -> int:
    if args.explain:
        return _explain(args.explain)
    if args.list_rules:
        for rule_id, rule in sorted(all_rules().items()):
            print(f"{rule_id} [{rule.category}/{rule.severity}]\n"
                  f"    {rule.description}")
        for rule_id, rule in sorted(all_flow_rules().items()):
            print(f"{rule_id} [flow:{rule.category}/{rule.severity}]"
                  f"\n    {rule.description}")
        for rule_id, rule in sorted(all_project_rules().items()):
            tag = getattr(rule, "layer", "project")
            print(f"{rule_id} [{tag}:{rule.category}/{rule.severity}]"
                  f"\n    {rule.description}")
        return 0
    try:
        file_select, project_select = _split_select(args.select)
    except KeyError as e:
        # KeyError's str() wraps its message in quotes; unwrap
        print(f"rafiki-tpu lint: {e.args[0]}", file=sys.stderr)
        return 2
    try:
        file_paths = list(args.paths)
        if args.changed_only is not None:
            file_paths = _scope_to_changed(
                file_paths, _changed_files(args.changed_only))
        findings = []
        if file_select != [] and file_paths:
            findings.extend(analyze_paths(
                file_paths, select=file_select,
                with_suppressed=args.show_suppressed))
        if args.project and project_select != []:
            findings.extend(analyze_project(
                args.paths, select=project_select,
                with_suppressed=args.show_suppressed))
    except OSError as e:
        # str(OSError) keeps errno text AND the path; a rule bug
        # (any other exception) propagates with its traceback instead
        # of masquerading as a usage error
        print(f"rafiki-tpu lint: {e}", file=sys.stderr)
        return 2
    # the per-module and project passes both report parse errors for
    # the same broken file — dedupe before rendering
    findings = list(dict.fromkeys(findings))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if args.format == "json":
        print(render_json(findings))
    elif args.format == "sarif":
        print(render_sarif(findings))
    elif findings:
        print(render_text(findings))
    else:
        print("clean: no findings")
    return 1 if findings else 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="rafiki-tpu-lint",
        description="JAX/concurrency-aware static analysis for the "
                    "rafiki-tpu codebase (see docs/linting.md)")
    add_lint_arguments(parser)
    return run_lint(parser.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
