"""Command-line front end for the lint engine.

Three consumers share this module: ``rafiki-tpu lint`` (the subcommand
in :mod:`rafiki_tpu.cli`), the ``rafiki-tpu-lint`` console entry
(pyproject), and ``scripts/lint.py`` (repo checkout, no install). All
of them parse the same flags and exit with the same contract:

- 0 — no unsuppressed findings (the CI gate passes)
- 1 — findings (printed to stdout, text or ``--format json``)
- 2 — usage/IO error (bad rule id, unreadable path)
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .engine import (all_rules, analyze_paths, get_rule, render_json,
                     render_text)


def add_lint_arguments(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "paths", nargs="*", default=["rafiki_tpu"],
        help="files or directories to analyze (default: rafiki_tpu)")
    parser.add_argument(
        "--format", choices=["text", "json"], default="text",
        help="finding output format")
    parser.add_argument(
        "--select", default=None, metavar="RULE[,RULE...]",
        help="run only these rule ids (default: all registered rules)")
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="include findings silenced by `# rafiki: noqa[...]` "
             "comments (they then count toward the exit code — an "
             "audit mode, not the CI gate)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the registered rules and exit")


def run_lint(args: argparse.Namespace) -> int:
    if args.list_rules:
        for rule_id, rule in sorted(all_rules().items()):
            print(f"{rule_id} [{rule.category}/{rule.severity}]\n"
                  f"    {rule.description}")
        return 0
    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        try:
            for rule_id in select:  # validate ids up front: usage error
                get_rule(rule_id)
        except KeyError as e:
            # KeyError's str() wraps its message in quotes; unwrap
            print(f"rafiki-tpu lint: {e.args[0]}", file=sys.stderr)
            return 2
    try:
        findings = analyze_paths(args.paths, select=select,
                                 with_suppressed=args.show_suppressed)
    except OSError as e:
        # str(OSError) keeps errno text AND the path; a rule bug
        # (any other exception) propagates with its traceback instead
        # of masquerading as a usage error
        print(f"rafiki-tpu lint: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(render_json(findings))
    elif findings:
        print(render_text(findings))
    else:
        print("clean: no findings")
    return 1 if findings else 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="rafiki-tpu-lint",
        description="JAX/concurrency-aware static analysis for the "
                    "rafiki-tpu codebase (see docs/linting.md)")
    add_lint_arguments(parser)
    return run_lint(parser.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
