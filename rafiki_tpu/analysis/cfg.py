"""Per-function control-flow graphs for the flow rules.

The per-module rules (:mod:`.engine`) and project rules
(:mod:`.project`) are pattern matchers: they see shapes, not *paths*.
The bug classes this third layer exists for — a ``release()`` missing
on the exception path, a buffer read after it was donated to a
compiled call, wire data reaching config on one branch only — are
properties of paths, so they need a CFG.

The graph is statement-level: a :class:`Block` holds a run of
statements that execute together; compound statements (``if``,
``while``, ``for``, ``try``, ``with``, ``match``) terminate their
block, with the compound node itself appended last so rules can
inspect its test/iterator/context expressions in evaluation position.
Edges carry a ``kind`` the witness renderer turns into English:
``flow``, ``true``/``false`` (branches), ``loop`` (back edge),
``exc`` (an exception raised somewhere in the source block),
``break``/``continue``/``return``/``raise`` (abrupt completion).

``try``/``finally`` is modeled with ONE instance of the finally body
and *kind-matched continuations*: every route out of the protected
region (normal completion, ``return``, ``break``, an exception)
enters the finally entry with its own edge kind, and the finally's
normal exit fans out through ``fin:<kind>``-tagged edges to each
continuation that entered it. Path walkers
(:func:`rafiki_tpu.analysis.dataflow.path_search`) keep a stack of
entry kinds so a path that entered the finally normally cannot leave
it on the exception continuation — the classic false-path of
single-instance finally modeling. A ``return`` inside the finally
itself overrides pending continuations, exactly like CPython.

Exception edges are block-granular: every block built inside a
``try`` gets one ``exc`` successor per reachable handler entry (plus
the adjacent finally entry, since no handler may match), meaning
"some statement here raised". Rules that care which *statement*
raised treat the ``exc`` successor as available from any statement
that can actually raise (one containing a call) — conservative in
the direction lint wants.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

__all__ = ["Block", "CFG", "build_cfg", "EDGE_NOTES"]

#: edge kind -> phrase used in witness traces (``fin:`` fan-outs
#: reuse the base kind's phrase)
EDGE_NOTES = {
    "flow": "then",
    "true": "when the branch is taken",
    "false": "when the branch is not taken",
    "loop": "looping back",
    "exc": "if this raises",
    "break": "breaking out of the loop",
    "continue": "continuing the loop",
    "return": "returning",
    "raise": "raising",
}


class Block:
    """One basic block: statements plus typed successor edges."""

    __slots__ = ("id", "stmts", "succs", "preds")

    def __init__(self, bid: int):
        self.id = bid
        self.stmts: List[ast.AST] = []
        self.succs: List[Tuple["Block", str]] = []
        self.preds: List[Tuple["Block", str]] = []

    def edge_to(self, other: "Block", kind: str = "flow") -> None:
        for b, k in self.succs:
            if b is other and k == kind:
                return
        self.succs.append((other, kind))
        other.preds.append((self, kind))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        succ = ", ".join(f"{k}->{b.id}" for b, k in self.succs)
        return f"<Block {self.id} [{len(self.stmts)} stmt] {succ}>"


class CFG:
    """The graph for one function: entry, exit, and every block."""

    def __init__(self, fn: ast.AST, entry: Block, exit_block: Block,
                 blocks: List[Block], finally_entries: Set[int]):
        self.fn = fn
        self.entry = entry
        self.exit = exit_block
        self.blocks = blocks
        #: ids of blocks that are finally-body entries — path walkers
        #: push the entry edge's kind here and pop it at the matching
        #: ``fin:<kind>`` fan-out edge
        self.finally_entries = finally_entries

    def statements(self) -> Iterator[Tuple[Block, int, ast.AST]]:
        """Every (block, index, statement) triple, in block order."""
        for block in self.blocks:
            for i, stmt in enumerate(block.stmts):
                yield block, i, stmt


def _can_raise(stmt: ast.AST) -> bool:
    """Can executing this statement plausibly raise? Anything that
    calls, raises, asserts, or indexes can; pure name/constant moves
    cannot (for lint purposes)."""
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Call, ast.Raise, ast.Assert,
                             ast.Subscript, ast.Await, ast.Yield,
                             ast.YieldFrom)):
            return True
    return False


class _FinallyFrame:
    """One open ``try``'s finally body, collecting the continuations
    routed through it (kind-matched)."""

    def __init__(self, entry: Block):
        self.entry = entry
        #: (continuation block, base edge kind) — fan-out becomes a
        #: ``fin:<kind>`` edge from the finally's normal exit
        self.targets: List[Tuple[Block, str]] = []
        self.saw_exc = False  # an exc/raise route entered this frame

    def add_target(self, block: Block, kind: str) -> None:
        for b, k in self.targets:
            if b is block and k == kind:
                return
        self.targets.append((block, kind))


class _Builder:
    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.blocks: List[Block] = []
        self.exit = self._new()
        self.entry = self._new()
        self.cur: Block = self.entry
        self.finally_entries: Set[int] = set()
        # control stack entries:
        #   ("loop", break_target, continue_target)
        #   ("except", [handler entry blocks])
        #   ("finally", _FinallyFrame)
        self.stack: List[tuple] = []

    # ---- plumbing ----

    def _new(self) -> Block:
        b = Block(len(self.blocks))
        self.blocks.append(b)
        return b

    def _start(self, block: Optional[Block] = None) -> Block:
        """Begin filling ``block`` (or a fresh one), wiring the
        current exception targets as ``exc`` successors."""
        b = block if block is not None else self._new()
        for target, frame in self._exc_targets():
            b.edge_to(target, "exc")
            if frame is not None:
                frame.saw_exc = True
        self.cur = b
        return b

    def _exc_targets(self) -> List[Tuple[Block, Optional["_FinallyFrame"]]]:
        """Where an exception raised *here* can transfer control: the
        innermost handlers, plus their try's adjacent finally entry
        (no handler may match)."""
        out: List[Tuple[Block, Optional[_FinallyFrame]]] = []
        for entry in reversed(self.stack):
            if entry[0] == "except":
                out.extend((h, None) for h in entry[1])
                continue  # the paired finally sits just beneath
            if entry[0] == "finally":
                out.append((entry[1].entry, entry[1]))
                break
            if out:
                break
        return out

    def _terminate(self) -> None:
        """Current block ended abruptly; subsequent statements (dead
        code) land in a fresh unreachable block."""
        self.cur = self._new()
        # deliberately no exc edges: the block is unreachable

    # ---- abrupt-completion routing (through finallys) ----

    def _route(self, kind: str, target: Block,
               until: Optional[tuple] = None) -> None:
        """Jump from ``self.cur`` to ``target`` with edge ``kind``,
        detouring through every open finally between here and
        ``until`` (a stack entry) / the stack bottom."""
        hops: List[_FinallyFrame] = []
        for entry in reversed(self.stack):
            if until is not None and entry is until:
                break
            if entry[0] == "finally":
                hops.append(entry[1])
        if not hops:
            self.cur.edge_to(target, kind)
            return
        self.cur.edge_to(hops[0].entry, kind)
        for inner, outer in zip(hops, hops[1:]):
            inner.add_target(outer.entry, kind)
        hops[-1].add_target(target, kind)

    def _route_raise(self) -> None:
        """An explicit ``raise``: to the innermost handlers, chaining
        through finallys; to the exit when nothing catches."""
        prev: Optional[_FinallyFrame] = None

        def _to(block: Block) -> None:
            if prev is None:
                self.cur.edge_to(block, "raise")
            else:
                prev.add_target(block, "raise")

        for entry in reversed(self.stack):
            if entry[0] == "except":
                for h in entry[1]:
                    _to(h)
                return
            if entry[0] == "finally":
                frame = entry[1]
                _to(frame.entry)
                frame.saw_exc = True
                prev = frame
        _to(self.exit)

    def _innermost_loop(self) -> Optional[tuple]:
        for entry in reversed(self.stack):
            if entry[0] == "loop":
                return entry
        return None

    # ---- statement dispatch ----

    def build(self) -> CFG:
        self._start(self.entry)
        self._body(self.fn.body)
        self.cur.edge_to(self.exit, "flow")
        return CFG(self.fn, self.entry, self.exit, self.blocks,
                   self.finally_entries)

    def _body(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.If):
            self._if(stmt)
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            self._loop(stmt)
        elif isinstance(stmt, ast.Try):
            self._try(stmt)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._with(stmt)
        elif isinstance(stmt, ast.Match):
            self._match(stmt)
        elif isinstance(stmt, ast.Return):
            self.cur.stmts.append(stmt)
            self._route("return", self.exit)
            self._terminate()
        elif isinstance(stmt, ast.Raise):
            self.cur.stmts.append(stmt)
            self._route_raise()
            self._terminate()
        elif isinstance(stmt, ast.Break):
            self.cur.stmts.append(stmt)
            loop = self._innermost_loop()
            if loop is not None:
                self._route("break", loop[1], until=loop)
            self._terminate()
        elif isinstance(stmt, ast.Continue):
            self.cur.stmts.append(stmt)
            loop = self._innermost_loop()
            if loop is not None:
                self._route("continue", loop[2], until=loop)
            self._terminate()
        else:
            # simple statement (incl. nested def/class: their bodies
            # get their own CFGs; the def itself is one binding stmt)
            self.cur.stmts.append(stmt)

    # ---- compound statements ----

    def _if(self, stmt: ast.If) -> None:
        self.cur.stmts.append(stmt)
        head = self.cur
        after = self._new()
        self._start()
        head.edge_to(self.cur, "true")
        self._body(stmt.body)
        self.cur.edge_to(after, "flow")
        if stmt.orelse:
            self._start()
            head.edge_to(self.cur, "false")
            self._body(stmt.orelse)
            self.cur.edge_to(after, "flow")
        else:
            head.edge_to(after, "false")
        self._start(after)

    def _loop(self, stmt) -> None:
        head = self._new()
        self.cur.edge_to(head, "flow")
        self._start(head)
        head.stmts.append(stmt)  # test / iterator evaluates here
        after = self._new()
        body = self._new()
        head.edge_to(body, "true")
        self.stack.append(("loop", after, head))
        self._start(body)
        self._body(stmt.body)
        self.cur.edge_to(head, "loop")
        self.stack.pop()
        if stmt.orelse:
            self._start()
            head.edge_to(self.cur, "false")
            self._body(stmt.orelse)
            self.cur.edge_to(after, "flow")
        else:
            head.edge_to(after, "false")
        self._start(after)

    def _with(self, stmt) -> None:
        self.cur.stmts.append(stmt)  # context exprs evaluate here
        body = self._new()
        self.cur.edge_to(body, "flow")
        self._start(body)
        self._body(stmt.body)
        after = self._new()
        self.cur.edge_to(after, "flow")
        self._start(after)

    def _match(self, stmt: ast.Match) -> None:
        self.cur.stmts.append(stmt)
        head = self.cur
        after = self._new()
        for case in stmt.cases:
            self._start()
            head.edge_to(self.cur, "true")
            self._body(case.body)
            self.cur.edge_to(after, "flow")
        head.edge_to(after, "false")  # no case matched
        self._start(after)

    def _try(self, stmt: ast.Try) -> None:
        after = self._new()
        fin_frame: Optional[_FinallyFrame] = None
        if stmt.finalbody:
            fin_frame = _FinallyFrame(self._new())
            self.finally_entries.add(fin_frame.entry.id)
            self.stack.append(("finally", fin_frame))
        handler_entries = [self._new() for _ in stmt.handlers]
        if handler_entries:
            self.stack.append(("except", handler_entries))

        body = self._new()
        self.cur.edge_to(body, "flow")
        self._start(body)
        self._body(stmt.body)
        if stmt.orelse:
            self._body(stmt.orelse)
        end_of_try = self.cur
        if handler_entries:
            self.stack.pop()  # handler bodies raise to the OUTER try

        # normal completion of try/else: through THIS finally only
        self.cur = end_of_try
        self._normal_completion(fin_frame, after)
        for handler, entry in zip(stmt.handlers, handler_entries):
            self._start(entry)
            if handler.type is not None or handler.name:
                entry.stmts.append(handler)  # anchor `except X as e:`
            self._body(handler.body)
            self._normal_completion(fin_frame, after)

        if fin_frame is not None:
            self.stack.pop()
            if fin_frame.saw_exc:
                # an unmatched exception that entered this finally
                # keeps unwinding afterwards: chain to the next
                # handler/finally outward, or the function exit
                save = self.cur
                self.cur = fin_frame.entry  # (unused by _route_raise
                #                              when prev is not None)
                prev = fin_frame
                done = False
                for entry in reversed(self.stack):
                    if entry[0] == "except":
                        for h in entry[1]:
                            prev.add_target(h, "raise")
                        done = True
                        break
                    if entry[0] == "finally":
                        prev.add_target(entry[1].entry, "raise")
                        entry[1].saw_exc = True
                        prev = entry[1]
                if not done and prev is not None:
                    prev.add_target(self.exit, "raise")
                self.cur = save
            self._start(fin_frame.entry)
            self._body(stmt.finalbody)
            # the finally's normal exit fans out, kind-matched, to
            # every continuation that routed through it; a finally
            # that itself completed abruptly already jumped and
            # leaves an unreachable `cur` (CPython's override)
            for target, kind in fin_frame.targets:
                self.cur.edge_to(target, "fin:" + kind)
        self._start(after)

    def _normal_completion(self, fin_frame: Optional[_FinallyFrame],
                           after: Block) -> None:
        if fin_frame is not None:
            self.cur.edge_to(fin_frame.entry, "flow")
            fin_frame.add_target(after, "flow")
        else:
            self.cur.edge_to(after, "flow")


def build_cfg(fn: ast.AST) -> CFG:
    """Build the CFG of one ``FunctionDef`` / ``AsyncFunctionDef``."""
    return _Builder(fn).build()
