"""Path/flow rules for compiled-call hazards.

``use-after-donate``: ``donate_argnums``/``donate_argnames`` hands a
buffer's storage to XLA — after the call the array is deleted, and
reading it raises (or silently aliases under some backends). The safe
idiom rebinds in the same statement (``params = step(params)``);
anything else that can reach a later read of the donated name on SOME
path is a bug only a path engine can see.

``jit-recompile-hazard``: a value that varies at runtime (clock reads,
``len()`` of mutable state, queue depths) flowing into a
``static_argnums``/``static_argnames`` position of a compiled call
recompiles on every new value — the process "works", 300ms slower per
step, forever. Bucketing/rounding helpers sanitize: a bucketed size
takes a handful of values, which is the whole point of buckets.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, Optional, Set, Tuple

from ..astutil import (JIT_NAMES, _const_ints, _const_strs, dotted,
                       param_names)
from ..dataflow import (FlowRule, TaintEngine, functions, has_source,
                        header_exprs, path_search, register_flow)


@dataclasses.dataclass
class _JitCallable:
    """A name that, when called in this module, runs a compiled fn."""

    params: list
    static: Set[str]
    donate_idx: Set[int]
    donate_names: Set[str]
    offset: int  # 1 when called bound (self.step(...)): arg i -> param i+1


def _donation_kwargs(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    idxs: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            idxs |= _const_ints(kw.value)
        elif kw.arg == "donate_argnames":
            names |= _const_strs(kw.value)
    return idxs, names


def jit_callables(ctx) -> Dict[str, _JitCallable]:
    """Map call-site spelling -> compiled-callable info.

    Covers decorated defs (``@jax.jit`` / ``@partial(jax.jit, ...)``,
    registered under ``name`` and ``self.name`` for methods) and
    wrapper assignments (``step = jax.jit(fn, ...)``, registered under
    the assign target, including ``self.step``). Memoized on the
    module context — both jit flow rules ask for it.
    """
    cached = ctx.memo.get("jit_callables")
    if cached is None:
        cached = ctx.memo["jit_callables"] = _jit_callables(ctx)
    return cached


def _jit_callables(ctx) -> Dict[str, _JitCallable]:
    out: Dict[str, _JitCallable] = {}
    for fn, info in ctx.traced().items():
        call = info.decorator if isinstance(info.decorator,
                                            ast.Call) else None
        d_idx, d_names = _donation_kwargs(call) if call else (set(),
                                                              set())
        if not (info.static_names or d_idx or d_names):
            continue
        params = param_names(fn)
        entry = _JitCallable(params, set(info.static_names),
                             d_idx, d_names, 0)
        out.setdefault(fn.name, entry)
        if params and params[0] in ("self", "cls"):
            out.setdefault("self." + fn.name, dataclasses.replace(
                entry, offset=1))
    # wrapper assignments: step = jax.jit(fn, donate_argnums=(0,))
    by_name = {n.name: n for n in ast.walk(ctx.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and dotted(node.value.func) in JIT_NAMES
                and node.value.args):
            continue
        target = node.value.args[0]
        fn = by_name.get(target.id) if isinstance(target,
                                                  ast.Name) else None
        if fn is None:
            continue
        params = param_names(fn)
        static: Set[str] = set()
        for kw in node.value.keywords:
            if kw.arg == "static_argnames":
                static |= _const_strs(kw.value)
            elif kw.arg == "static_argnums":
                static |= {params[i] for i in _const_ints(kw.value)
                           if 0 <= i < len(params)}
        d_idx, d_names = _donation_kwargs(node.value)
        if not (static or d_idx or d_names):
            continue
        for tgt in node.targets:
            name = dotted(tgt)
            if name is not None:
                out.setdefault(name, _JitCallable(
                    params, static, d_idx, d_names, 0))
    return out


def _var_path(node: ast.AST) -> Optional[str]:
    """A donated argument we can track: a bare name or self-ish
    attribute chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return dotted(node)
    return None


def _reads(stmt: ast.AST, path: str) -> bool:
    for part in header_exprs(stmt):
        for node in ast.walk(part):
            if "." in path:
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.ctx, ast.Load) and \
                        dotted(node) == path:
                    return True
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and node.id == path:
                return True
    return False


def _bind_targets(stmt: ast.AST):
    if isinstance(stmt, ast.Assign):
        return stmt.targets
    if isinstance(stmt, ast.AnnAssign):
        return [stmt.target]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.optional_vars for i in stmt.items if i.optional_vars]
    return []


def _rebinds(stmt: ast.AST, path: str) -> bool:
    for target in _bind_targets(stmt):
        for node in ast.walk(target):
            if isinstance(getattr(node, "ctx", None), ast.Store) and \
                    dotted(node) == path:
                return True
    return False


@register_flow
class UseAfterDonateRule(FlowRule):
    id = "use-after-donate"
    category = "jax"
    severity = "error"
    description = (
        "a buffer passed at a donate_argnums/donate_argnames position "
        "is read again on some later path: donation hands the storage "
        "to XLA, so the read sees a deleted (or silently aliased) "
        "array — rebind in the donating statement or drop the "
        "donation")
    sources = (
        "an argument at a donated position of a jit'd call "
        "(@jax.jit(donate_argnums=...) decorations and "
        "`step = jax.jit(fn, donate_argnums=...)` wrappers)",
    )
    sinks = (
        "any later read of that name on any path (including the next "
        "loop iteration) before it is rebound",
    )
    sanitizers = (
        "rebinding in the donating statement itself "
        "(`params = step(params)`) or on every path before the read",
    )
    example = (
        "def train_step(params, batch): ...\n"
        "step = jax.jit(train_step, donate_argnums=(0,))\n"
        "def loop(params, batches):\n"
        "    for b in batches:\n"
        "        loss = step(params, b)   # donates params...\n"
        "        log(loss)                # ...but never rebinds it:\n"
        "                                 # iteration 2 reads a freed "
        "buffer\n")

    def check(self, ctx) -> Iterator[Tuple[ast.AST, str, tuple]]:
        table = jit_callables(ctx)
        if not any(c.donate_idx or c.donate_names
                   for c in table.values()):
            return
        for fn, cfg in functions(ctx):
            for block, idx, stmt in cfg.statements():
                for part in header_exprs(stmt):
                    for call in ast.walk(part):
                        if isinstance(call, ast.Call):
                            yield from self._check_call(
                                cfg, block, idx, stmt, call, table)

    def _check_call(self, cfg, block, idx, stmt, call, table):
        info = table.get(dotted(call.func) or "")
        if info is None or not (info.donate_idx or info.donate_names):
            return
        donated = []
        for i, arg in enumerate(call.args):
            if (i + info.offset) in info.donate_idx:
                donated.append(arg)
            elif 0 <= i + info.offset < len(info.params) and \
                    info.params[i + info.offset] in info.donate_names:
                donated.append(arg)
        for kw in call.keywords:
            if kw.arg in info.donate_names:
                donated.append(kw.value)
        callee = dotted(call.func)
        for arg in donated:
            path = _var_path(arg)
            if path is None or _rebinds(stmt, path):
                continue  # `params = step(params)` — the safe idiom
            hits = path_search(
                cfg, block, idx + 1,
                kill=lambda s, p=path: _rebinds(s, p),
                hit=lambda s, p=path: (
                    f"'{p}' read here — the buffer was already "
                    f"donated" if _reads(s, p) else None))
            for h in hits:
                trace = self.trace_from_path(
                    stmt, f"'{path}' donated to '{callee}' here", h)
                yield stmt, (
                    f"'{path}' is donated to '{callee}' but read "
                    f"again at line {h.stmt.lineno} — donation frees "
                    f"the buffer, so that read sees deleted (or "
                    f"aliased) storage; rebind it in the donating "
                    f"statement or drop the donation"), trace
                break  # one witness per donated arg


@register_flow
class JitRecompileHazardRule(FlowRule):
    id = "jit-recompile-hazard"
    category = "jax"
    severity = "warning"
    description = (
        "a runtime-varying value (clock read, len() of mutable state, "
        "queue depth) flows into a static_argnums/static_argnames "
        "position of a compiled call: every new value is a new cache "
        "key, so the call silently recompiles per step — bucket or "
        "round the value, or make the argument dynamic")
    sources = (
        "time.time()/time.monotonic()/time.perf_counter() reads",
        "len() of a variable or attribute (mutable state)",
        ".qsize()/.stats()/.depth() queue and stats reads",
    )
    sinks = (
        "arguments at static positions of jit'd calls (resolved from "
        "static_argnums/static_argnames on decorations and wrappers)",
    )
    sanitizers = (
        "bucketing/rounding/padding helpers (any callable whose name "
        "contains bucket/round/pad/align) — a bucketed value takes "
        "few distinct values, which is what static args require",
    )
    example = (
        "def decode(batch, max_len): ...\n"
        "step = jax.jit(decode, static_argnames=('max_len',))\n"
        "def serve(self, batch):\n"
        "    n = len(self.pending)        # varies every call...\n"
        "    return step(batch, max_len=n)  # ...recompiles every "
        "call\n")

    _CLOCKS = {"time.time", "time.monotonic", "time.perf_counter",
               "time"}
    _STATS_ATTRS = ("qsize", "stats", "depth", "llen", "approx_len")
    _SANITIZE = ("bucket", "round", "pad", "align")

    def _source(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        name = dotted(node.func)
        if name in self._CLOCKS and not node.args:
            return f"runtime-varying clock read ({name}())"
        if isinstance(node.func, ast.Name) and node.func.id == "len" \
                and node.args and isinstance(
                    node.args[0], (ast.Name, ast.Attribute)):
            what = dotted(node.args[0]) or "state"
            return f"len({what}) varies with runtime state"
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in self._STATS_ATTRS:
            return f".{node.func.attr}() varies per call"
        return None

    def _sanitizer(self, call: ast.Call) -> bool:
        name = (dotted(call.func) or "").rsplit(".", 1)[-1].lower()
        return any(tok in name for tok in self._SANITIZE)

    def check(self, ctx) -> Iterator[Tuple[ast.AST, str, tuple]]:
        table = {name: info for name, info in jit_callables(ctx).items()
                 if info.static}
        if not table:
            return
        for fn, cfg in functions(ctx):
            if not has_source(fn, self._source):
                continue
            eng = TaintEngine(cfg, self._source, self._sanitizer).run()
            for block, idx, stmt in cfg.statements():
                for part in header_exprs(stmt):
                    for call in ast.walk(part):
                        if isinstance(call, ast.Call):
                            yield from self._check_call(
                                eng, stmt, call, table)

    def _check_call(self, eng, stmt, call, table):
        info = table.get(dotted(call.func) or "")
        if info is None:
            return
        callee = dotted(call.func)
        judged = []
        for i, arg in enumerate(call.args):
            pos = i + info.offset
            if 0 <= pos < len(info.params) and \
                    info.params[pos] in info.static:
                judged.append((info.params[pos], arg))
        for kw in call.keywords:
            if kw.arg in info.static:
                judged.append((kw.arg, kw.value))
        for pname, arg in judged:
            taint = eng.taint_at(arg, stmt)
            if taint is None:
                continue
            sink_note = (f"flows into static arg '{pname}' of "
                         f"'{callee}' — new value => recompile")
            yield arg, (
                f"runtime-varying value flows into static arg "
                f"'{pname}' of jit'd '{callee}': each distinct value "
                f"recompiles the function silently — bucket/round it "
                f"first, or drop it from static_argnums"), \
                self.trace_from_taint(taint, arg, sink_note)
