"""Taint rule replacing the name-heuristic ``wall-clock-deadline``.

The old per-module rule fired only when ``time.time()`` appeared
*textually inside* a deadline assignment or comparison — it missed
every flow through an intermediate variable (``now = time.time();
deadline = now + ttl``) and through module-local helpers
(``def _now(): return time.time()``). This version propagates real
taint through assignments, arithmetic, and one level of local
returns, so those flows are caught; and it knows the repo's two
sanctioned laundering paths — ``time.monotonic`` conversions and
ClockSkewEstimator-adjusted values — so the documented-fallback
suppression list gets *shorter*, not longer.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Tuple

from ..astutil import dotted
from ..dataflow import (FlowRule, TaintEngine, functions, has_source,
                        header_exprs, register_flow,
                        tainted_return_helpers)

_DEADLINE = re.compile(r"deadline|expir", re.IGNORECASE)


def _wall_source(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call) and not node.args and not node.keywords:
        if dotted(node.func) in ("time.time", "time"):
            return "wall-clock read (time.time())"
    return None


def _skew_sanitizer(call: ast.Call) -> bool:
    """Calls that convert a wall-clock value into a safe one: the
    monotonic-conversion helpers and anything on the ClockSkew path
    (estimator methods, skew_adjust helpers)."""
    name = (dotted(call.func) or "").lower()
    return "skew" in name or "monotonic" in name


def _deadline_target(target: ast.AST) -> bool:
    if isinstance(target, ast.Name):
        return bool(_DEADLINE.search(target.id))
    if isinstance(target, ast.Attribute):
        return bool(_DEADLINE.search(target.attr))
    if isinstance(target, ast.Subscript):
        sl = target.slice
        return (isinstance(sl, ast.Constant)
                and isinstance(sl.value, str)
                and bool(_DEADLINE.search(sl.value)))
    return False


@register_flow
class TaintWallClockFlowRule(FlowRule):
    id = "taint-wall-clock-flow"
    category = "robustness"
    severity = "warning"
    description = (
        "wall-clock time.time() flows (through assignments, "
        "arithmetic, or local helper returns) into a deadline/expiry "
        "value or comparison: clock steps and cross-host skew shift "
        "it silently — compute deadlines on time.monotonic(), or "
        "ship relative ttl_s judged through ClockSkewEstimator")
    sources = (
        "time.time() / bare time() calls",
        "calls to module-local helpers whose return value is "
        "wall-clock tainted (one level of propagation)",
    )
    sinks = (
        "assignments to deadline/expiry-named targets "
        "(`deadline = ...`, `self.expiry = ...`, `d['deadline'] = ...`)",
        "deadline/expiry-named dict keys and keyword arguments",
        "ordering comparisons (< <= > >=) with a tainted operand — "
        "a deadline test",
    )
    sanitizers = (
        "any call whose dotted name contains 'monotonic' or 'skew' "
        "(time.monotonic conversions, ClockSkewEstimator methods)",
    )
    example = (
        "def enqueue(self, ttl_s):\n"
        "    now = time.time()\n"
        "    self.deadline = now + ttl_s   # tainted through 'now'\n")

    _MSG = (
        "wall-clock time.time() taints this {what}: a clock step or "
        "cross-host skew shifts the deadline silently — compute it "
        "on time.monotonic(), or ship relative ttl_s + sent_ts "
        "judged through ClockSkewEstimator; suppress only the "
        "documented wall-clock FALLBACK paths")

    def check(self, ctx) -> Iterator[Tuple[ast.AST, str, tuple]]:
        helpers = tainted_return_helpers(ctx.tree, _wall_source,
                                         _skew_sanitizer)

        def source(node: ast.AST) -> Optional[str]:
            note = _wall_source(node)
            if note:
                return note
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name in helpers:
                    return (f"wall-clock value returned by "
                            f"'{name.rsplit('.', 1)[-1]}()'")
            return None

        # the skew estimator's own internals ARE the sanctioned
        # laundering path — its raw wall-clock math is the point
        skip = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and \
                    "skew" in node.name.lower():
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        skip.add(sub)

        for fn, cfg in functions(ctx):
            if fn in skip or not has_source(fn, source):
                continue
            eng = TaintEngine(cfg, source, _skew_sanitizer).run()
            for block, idx, stmt in cfg.statements():
                yield from self._check_stmt(eng, stmt)

    def _check_stmt(self, eng, stmt):
        state = eng.state_before(stmt)
        # sink 1: deadline-named assignment targets
        if isinstance(stmt, ast.Assign):
            taint = eng.eval(stmt.value, state)
            if taint is not None and any(_deadline_target(t)
                                         for t in stmt.targets):
                yield stmt, self._MSG.format(
                    what="deadline assignment"), self.trace_from_taint(
                        taint, stmt, "assigned to a deadline/expiry "
                        "name here")
        elif isinstance(stmt, ast.AugAssign):
            taint = eng.eval(stmt.value, state)
            if taint is not None and _deadline_target(stmt.target):
                yield stmt, self._MSG.format(
                    what="deadline assignment"), self.trace_from_taint(
                        taint, stmt, "folded into a deadline/expiry "
                        "name here")
        for part in header_exprs(stmt):
            for node in ast.walk(part):
                # sink 2: ORDERING comparisons — a deadline test.
                # Equality/membership/identity on a tainted value is
                # not a deadline judgment (sentinel checks, `k in d`).
                if isinstance(node, ast.Compare):
                    if not any(isinstance(op, (ast.Lt, ast.LtE,
                                               ast.Gt, ast.GtE))
                               for op in node.ops):
                        continue
                    for side in [node.left, *node.comparators]:
                        taint = eng.eval(side, state)
                        if taint is not None:
                            yield node, self._MSG.format(
                                what="comparison (deadline test)"), \
                                self.trace_from_taint(
                                    taint, node, "compared here — a "
                                    "wall-clock deadline test")
                            break
                # sink 3: deadline-named dict keys
                elif isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if (isinstance(k, ast.Constant)
                                and isinstance(k.value, str)
                                and _DEADLINE.search(k.value)
                                and v is not None):
                            taint = eng.eval(v, state)
                            if taint is not None:
                                yield v, self._MSG.format(
                                    what=f"dict entry "
                                    f"{k.value!r}"), \
                                    self.trace_from_taint(
                                        taint, v, f"stored under "
                                        f"dict key {k.value!r} here")
                # sink 4: deadline-named keyword arguments
                elif isinstance(node, ast.Call):
                    for kw in node.keywords:
                        if kw.arg is not None and \
                                _DEADLINE.search(kw.arg):
                            taint = eng.eval(kw.value, state)
                            if taint is not None:
                                yield kw.value, self._MSG.format(
                                    what=f"keyword argument "
                                    f"'{kw.arg}'"), \
                                    self.trace_from_taint(
                                        taint, kw.value,
                                        f"passed as keyword "
                                        f"'{kw.arg}' here")
