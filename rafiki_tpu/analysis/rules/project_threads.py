"""The thread-model rules: interprocedural race + lifecycle analysis.

Fourth analysis layer (``--list-rules`` tags these ``[threads:...]``):
per-module rules see shapes, flow rules see paths, project rules see
cross-layer contracts — these rules see *threads*. They fuse the
:class:`~rafiki_tpu.analysis.threads.ThreadModel` (which thread
contexts run each function) with
:class:`~rafiki_tpu.analysis.summaries.AccessSummaries` (what shared
state each function touches, under which must-held locks) and report:

- ``shared-state-race`` (error) — a field/global written in one
  thread context and accessed in another with disjoint locksets.
  Exemptions, in the order they are applied: internally-synchronized
  fields never produce accesses (queues, Events, locks, StatsMap, obs
  instruments — see :mod:`..summaries`); writes in a class's setup
  closure happen-before any root its constructor starts
  (init-before-``start()``); a bare ``self.flag = True``-style
  constant store observed only by reads is a GIL-atomic handoff, not
  a torn update.
- ``atomic-rmw-race`` (warning) — ``+=``-style read-modify-write on a
  shared target outside any lock: both interleavings of the read and
  the write lose updates even though no single access is torn.
- ``thread-lifecycle`` (error) — a class that starts a non-daemon
  thread/timer must join or cancel it on its close/stop path, or
  interpreter shutdown blocks on a thread nobody owns.

Race findings carry BOTH sides: ``Finding.threads`` holds one
spawn-site → call-chain → access stack per context, rendered as
paired traces in text and two ``threadFlows`` in one SARIF
``codeFlow``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from ..engine import TraceStep
from ..project import (ProjectContext, ProjectRule, register_project)

# NOTE: ..threads / ..summaries are imported lazily inside the
# functions below — they import shared vocabulary back out of this
# rules package, so a module-level import here would be circular.

#: methods that constitute a component's teardown path
_CLOSERS = {"close", "stop", "shutdown", "join", "terminate",
            "cancel", "__exit__", "__del__", "aclose"}


def _analysis(project: ProjectContext
              ) -> Tuple[ThreadModel, AccessSummaries]:
    """The (thread model, access summaries) pair, computed once per
    project and shared by all three rules via ``project.memo``."""
    from ..summaries import AccessSummaries
    from ..threads import ThreadModel
    if "project_threads" not in project.memo:
        model = ThreadModel(project)
        project.memo["project_threads"] = \
            (model, AccessSummaries(project, model))
    return project.memo["project_threads"]


def _race_pairs(project: ProjectContext
                ) -> Dict[str, Tuple[Access, Access, str, str]]:
    """target -> best (write, other access, ctx-of-write, ctx-of-other)
    conflicting pair, memoized — ``shared-state-race`` reports these
    and ``atomic-rmw-race`` skips their targets."""
    if "race_pairs" in project.memo:
        return project.memo["race_pairs"]
    model, summ = _analysis(project)
    out: Dict[str, Tuple[Access, Access, str, str]] = {}
    for target in sorted(summ.by_target):
        pair = _best_pair(model, summ.by_target[target])
        if pair is not None:
            out[target] = pair
    project.memo["race_pairs"] = out
    return out


def _best_pair(model: ThreadModel, accesses: List[Access]
               ) -> Optional[Tuple[Access, Access, str, str]]:
    from ..threads import MAIN
    best = None
    best_score = -1
    for w in accesses:
        if w.kind == "read":
            continue
        cw = model.contexts_of(w.func)
        if not cw:
            continue
        for a in accesses:
            if (a.path, a.line) == (w.path, w.line):
                continue  # one site racing itself is rmw territory
            if w.locks & a.locks:
                continue  # a common lock orders them
            if w.atomic and (a.kind == "read" or a.atomic):
                continue  # GIL-atomic constant store / flag handoff
            for ca in sorted(cw):
                for cb in sorted(model.contexts_of(a.func)):
                    if ca == cb and (not model.is_multi(ca) or
                                     w.func == a.func):
                        # same single-instance context is ordered;
                        # one function racing its own multi-instance
                        # self is atomic-rmw-race's report
                        continue
                    if model.happens_before(w.func, w.line, cb) or \
                            model.happens_before(a.func, a.line, ca):
                        continue  # init-before-start()
                    score = (ca != MAIN) + (cb != MAIN) + \
                        (a.kind != "read")
                    if score > best_score:
                        best, best_score = (w, a, ca, cb), score
    return best


def _access_step(a: Access, target: str, verb: str) -> TraceStep:
    locks = ("holding " + "/".join(
        sorted(lock.rsplit(":", 1)[-1] for lock in a.locks))
        if a.locks else "with no lock held")
    return TraceStep(
        a.line, a.col,
        f"'{_short(a.func)}' {verb} '{_short(target)}' {locks}",
        a.path)


def _stack(model: ThreadModel, label: str, a: Access, target: str,
           verb: str) -> Tuple[str, tuple]:
    return (label, model.trace(label, a.func)
            + (_access_step(a, target, verb),))


def _verb(a: Access) -> str:
    return {"read": "reads", "write": "writes",
            "rmw": "read-modify-writes"}[a.kind]


def _short(name: str) -> str:
    return name.rsplit(":", 1)[-1]


@register_project
class SharedStateRaceRule(ProjectRule):
    id = "shared-state-race"
    category = "concurrency"
    severity = "error"
    layer = "threads"
    description = (
        "a field or module global written in one thread context and "
        "accessed in another with disjoint locksets: the interleaving "
        "the GIL happens to allow today decides what the reader sees "
        "— guard both sides with one lock (supersedes the per-module "
        "inconsistent-lock / thread-unlocked-global rules)")

    example = (
        "import threading\n"
        "\n"
        "\n"
        "class Buffer:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._items = {}\n"
        "        self._t = threading.Thread(target=self._drain,\n"
        "                                   daemon=True)\n"
        "        self._t.start()\n"
        "\n"
        "    def put(self, key, value):\n"
        "        with self._lock:\n"
        "            self._items[key] = value\n"
        "\n"
        "    def _drain(self):\n"
        "        while self._items:\n"
        "            self._items.clear()  # no lock: races put()\n")

    def check(self, project: ProjectContext) -> Iterator[tuple]:
        model, _summ = _analysis(project)
        for target, (w, a, ca, cb) in sorted(
                _race_pairs(project).items()):
            w_locks = "/".join(sorted(
                lock.rsplit(":", 1)[-1] for lock in w.locks)) or "none"
            a_locks = "/".join(sorted(
                lock.rsplit(":", 1)[-1] for lock in a.locks)) or "none"
            yield (w.path, w.line, w.col,
                   f"'{_short(target)}' is written by "
                   f"'{_short(w.func)}' [{ca}] and "
                   f"{'written' if a.kind != 'read' else 'read'} by "
                   f"'{_short(a.func)}' [{cb}] with disjoint locksets "
                   f"({w_locks} vs {a_locks}) — the two threads "
                   "interleave freely; guard both sides with one lock",
                   (_stack(model, ca, w, target, _verb(w)),
                    _stack(model, cb, a, target, _verb(a))))


@register_project
class AtomicRmwRaceRule(ProjectRule):
    id = "atomic-rmw-race"
    category = "concurrency"
    severity = "warning"
    layer = "threads"
    description = (
        "+= / read-modify-write on a shared field outside any lock: "
        "no single access is torn, but two threads interleaving the "
        "read and the write lose updates — wrap the whole "
        "read-modify-write in a lock")

    example = (
        "class Api:\n"
        "    def __init__(self, svc):\n"
        "        self.hits = 0\n"
        "        svc.route('GET', '/stats', self._stats)\n"
        "\n"
        "    def _stats(self, request):\n"
        "        self.hits += 1  # two handler threads lose updates\n"
        "        return {'hits': self.hits}\n")

    def check(self, project: ProjectContext) -> Iterator[tuple]:
        model, summ = _analysis(project)
        raced = _race_pairs(project)
        for target in sorted(summ.by_target):
            if target in raced:
                continue  # already reported as a full race
            for a in summ.by_target[target]:
                if a.kind != "rmw" or a.locks:
                    continue
                ctxs = sorted(model.contexts_of(a.func))
                multi = [c for c in ctxs if model.is_multi(c)]
                if not multi and len(ctxs) < 2:
                    continue
                if multi:
                    how = (f"two instances of [{multi[0]}] interleave "
                           "the read and the write")
                    labels = (multi[0], multi[0])
                else:
                    how = (f"[{ctxs[0]}] and [{ctxs[1]}] interleave "
                           "the read and the write")
                    labels = (ctxs[0], ctxs[1])
                yield (a.path, a.line, a.col,
                       f"read-modify-write of '{_short(target)}' in "
                       f"'{_short(a.func)}' holds no lock: {how} and "
                       "updates are lost — make the whole "
                       "read-modify-write atomic under a lock",
                       tuple(_stack(model, label, a, target,
                                    "read-modify-writes")
                             for label in labels))
                break  # one finding per target


@register_project
class ThreadLifecycleRule(ProjectRule):
    id = "thread-lifecycle"
    category = "concurrency"
    severity = "error"
    layer = "threads"
    description = (
        "a component that starts a non-daemon thread or timer must "
        "join/cancel it on its close()/stop() path — otherwise "
        "interpreter shutdown blocks on a thread nobody owns (make "
        "it daemon= if it truly has no teardown contract)")

    example = (
        "import queue\n"
        "import threading\n"
        "\n"
        "\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self._q = queue.Queue()\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "        self._t.start()\n"
        "\n"
        "    def _run(self):\n"
        "        while True:\n"
        "            if self._q.get() is None:\n"
        "                return\n"
        "\n"
        "    def close(self):\n"
        "        self._q.put(None)  # stops the loop, never join()s\n")

    def check(self, project: ProjectContext) -> Iterator[tuple]:
        model, _summ = _analysis(project)
        for root in model.roots:
            if root.kind not in ("thread", "timer") or root.daemon:
                continue
            if root.spawner is None:
                continue  # module-level scripts own their threads
            sp = model.functions.get(root.spawner)
            if sp is None or sp.cls is None:
                continue  # free-function spawner: caller's contract
            if self._join_on_close_path(project, sp.cls):
                continue
            yield (root.path, root.line, root.col,
                   f"'{_short(sp.cls)}.{_method(root.spawner)}' starts "
                   f"non-daemon {root.kind} '{root.name}' but no "
                   "close/stop/shutdown path joins or cancels it — "
                   "interpreter exit will hang on it; join it in "
                   "close() (or pass daemon=True if it has no "
                   "teardown contract)")

    @staticmethod
    def _join_on_close_path(project: ProjectContext,
                            cls_q: str) -> bool:
        """Does any teardown method (or a helper it calls on
        ``self``) contain a ``.join(...)`` / ``.cancel(...)``?"""
        from ..threads import walk_own
        methods: Dict[str, ast.AST] = {}
        for c in project.class_mro(cls_q):
            for name, node in c.methods.items():
                methods.setdefault(name, node)
        seen = set()
        frontier = [n for n in methods if n in _CLOSERS]
        while frontier:
            name = frontier.pop()
            if name in seen:
                continue
            seen.add(name)
            for node in walk_own(methods[name]):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute):
                    if node.func.attr in ("join", "cancel"):
                        return True
                    if isinstance(node.func.value, ast.Name) and \
                            node.func.value.id == "self" and \
                            node.func.attr in methods:
                        frontier.append(node.func.attr)
        return False


def _method(qualname: str) -> str:
    return qualname.rsplit(".", 1)[-1]
