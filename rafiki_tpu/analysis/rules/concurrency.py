"""Shared concurrency vocabulary for the analysis layers.

This module used to host two per-module rules, ``inconsistent-lock``
and ``thread-unlocked-global``. Both were retired in favor of the
interprocedural thread-model layer
(:mod:`.project_threads`): the per-module versions could only vote on
lock discipline inside one class body and guess thread targets inside
one file, so they missed every cross-module race and flagged
single-owner mirrors. Their ``# rafiki: noqa[...]`` ids still apply —
:data:`~rafiki_tpu.analysis.engine.RULE_ALIASES` maps them onto
``shared-state-race`` / ``atomic-rmw-race``.

What remains here is the vocabulary the newer layers share: which
constructors build locks, which container methods mutate their
receiver, and which names a function binds locally (and therefore
shadow module globals).
"""

from __future__ import annotations

import ast
from typing import Set

#: constructors whose result is a lock-ish guard object
_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "Lock", "RLock", "Condition",
}

#: container methods that mutate the receiver
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "appendleft",
}


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Names rebound locally (parameters or plain assignments), which
    therefore shadow any same-named module global — unless declared
    ``global``."""
    out: Set[str] = set()
    globals_: Set[str] = set()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        out.add(p.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            globals_.update(node.names)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out - globals_
