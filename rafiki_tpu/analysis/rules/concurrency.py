"""Concurrency-hazard rules.

The serving/worker stack is thread-heavy (decode loop, micro-batcher,
heartbeats, services manager, SSE writers), and every one of the
observed races had the same shape: state that is CLEARLY meant to be
lock-protected — because the same class protects it elsewhere — written
without the lock, or module globals mutated straight from a thread
target. Both are invisible to type checkers; both are mechanical to
find in the AST.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..astutil import dotted
from ..engine import Rule, register

#: constructors whose result is a lock-ish guard object
_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "Lock", "RLock", "Condition",
}

#: container methods that mutate the receiver
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "appendleft",
}


def _with_lock_exprs(node: ast.With) -> List[str]:
    return [dotted(item.context_expr) or
            (dotted(item.context_expr.func) or ""
             if isinstance(item.context_expr, ast.Call) else "")
            for item in node.items]


def _lockish(name: str, known_locks: Set[str]) -> bool:
    """Does this with-context expression look like acquiring a lock?

    ``known_locks`` holds attribute paths assigned a Lock/Condition in
    the same class (exact match); beyond those, any name containing
    lock/mutex/cv/cond counts — the rule must not fire on code that is
    visibly TRYING to lock, even through an alias we can't resolve.
    """
    if name in known_locks:
        return True
    lowered = name.rsplit(".", 1)[-1].lower()
    return any(tok in lowered for tok in ("lock", "mutex", "cv", "cond",
                                          "sem"))


class _FunctionScanner:
    """Classifies every write inside one function/method body as
    locked (within a ``with <lock>``) or bare."""

    def __init__(self, fn: ast.AST, known_locks: Set[str]):
        self.fn = fn
        self.known_locks = known_locks
        # write target path -> list of (node, locked?)
        self.writes: List[Tuple[str, ast.AST, bool]] = []
        self._scan(fn.body, locked=False)

    def _scan(self, body, locked: bool) -> None:
        for node in body:
            if isinstance(node, ast.With):
                inner = locked or any(
                    _lockish(n, self.known_locks)
                    for n in _with_lock_exprs(node) if n)
                self._scan(node.body, inner)
                continue
            self._record(node, locked)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # nested defs have their own discipline
                self._scan([child], locked)

    def _record(self, node: ast.AST, locked: bool) -> None:
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            path = dotted(node.func.value)
            if path:
                self.writes.append((path, node, locked))
            return
        for t in targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value  # d[k] = v writes d
            path = dotted(base)
            if path:
                self.writes.append((path, node, locked))


def _class_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attribute paths (``self.X``) assigned a Lock/Condition anywhere
    in the class body."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not (isinstance(value, ast.Call)
                and dotted(value.func) in _LOCK_CTORS):
            continue
        for t in node.targets:
            path = dotted(t)
            if path:
                out.add(path)
    return out


@register
class InconsistentLockRule(Rule):
    id = "inconsistent-lock"
    category = "concurrency"
    severity = "error"
    description = (
        "attribute written under the class's lock everywhere else but "
        "bare in one method: either that write is a race or the "
        "discipline is an illusion — both deserve a look")

    #: methods allowed to write anything bare: construction happens
    #: before the object is shared, and teardown after.
    _SETUP = {"__init__", "__new__", "__enter__", "__post_init__"}

    def check(self, ctx):
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _class_lock_attrs(cls)
            if not locks:
                continue
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            # Eraser-style lockset vote per attribute: an attr counts
            # as lock-protected only when bare writes are a strict
            # minority (< 1/3 of non-setup writes). Classes that hold
            # a lock for a narrow handoff while a single owner thread
            # writes its private mirrors bare (the decode engine) vote
            # those attrs "unprotected" and stay clean; one stray bare
            # write against an otherwise-locked attr gets flagged.
            setup = self._setup_methods(methods)
            locked_by: Dict[str, str] = {}  # attr -> a locking method
            counts: Dict[str, List[int]] = {}  # attr -> [locked, bare]
            bare_sites = []
            for m in methods:
                scan = _FunctionScanner(m, locks)
                is_setup = m.name in setup
                holds_by_name = m.name.endswith("_locked")
                for path, node, locked in scan.writes:
                    if not path.startswith("self.") or path in locks:
                        continue
                    if is_setup:
                        continue  # object not shared yet
                    if locked or holds_by_name:
                        counts.setdefault(path, [0, 0])[0] += 1
                        if locked:
                            locked_by.setdefault(path, m.name)
                    else:
                        counts.setdefault(path, [0, 0])[1] += 1
                        bare_sites.append((path, node, m.name))
            for path, node, method in bare_sites:
                n_locked, n_bare = counts[path]
                if path not in locked_by or locked_by[path] == method:
                    continue
                if n_bare * 2 > n_locked:
                    continue  # attr votes "not lock-protected"
                yield node, (
                    f"'{path}' is written under "
                    f"{'/'.join(sorted(locks))} in "
                    f"'{cls.name}.{locked_by[path]}' (and "
                    f"{n_locked} locked write(s) total) but bare here "
                    f"in '{method}' — hold the lock (or rename the "
                    "method *_locked if the caller holds it)")

    @classmethod
    def _setup_methods(cls, methods) -> Set[str]:
        """Constructor closure: ``__init__`` etc. plus helpers every
        one of whose in-class callers is itself setup — the object is
        not shared with other threads while they run."""
        names = {m.name for m in methods}
        callers: Dict[str, Set[str]] = {n: set() for n in names}
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self" and \
                        node.func.attr in callers:
                    callers[node.func.attr].add(m.name)
        setup = set(cls._SETUP)
        changed = True
        while changed:
            changed = False
            for name in names - setup:
                if callers[name] and callers[name] <= setup:
                    setup.add(name)
                    changed = True
        return setup


def _thread_target_names(tree: ast.Module) -> Dict[str, ast.AST]:
    """Function/method names passed as ``Thread(target=...)`` (plus
    ``start_new_thread``/executor ``submit`` forms) in this module."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func) or ""
        candidates: List[ast.AST] = []
        if fname.endswith("Thread") or fname.endswith("Timer"):
            candidates += [kw.value for kw in node.keywords
                           if kw.arg == "target"]
        elif fname.rsplit(".", 1)[-1] == "submit" and node.args:
            candidates.append(node.args[0])
        for cand in candidates:
            path = dotted(cand)
            if path:
                out[path.rsplit(".", 1)[-1]] = node
    return out


@register
class ThreadUnlockedGlobalRule(Rule):
    id = "thread-unlocked-global"
    category = "concurrency"
    severity = "error"
    description = (
        "module-level mutable state mutated inside a thread target "
        "without any lock held: a data race the GIL only hides until "
        "the interleaving changes")

    _MUTABLE_CTORS = {"dict", "list", "set", "collections.defaultdict",
                      "defaultdict", "collections.OrderedDict",
                      "OrderedDict", "collections.deque", "deque",
                      "Counter", "collections.Counter"}

    def _module_mutables(self, tree: ast.Module) -> Set[str]:
        out: Set[str] = set()
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            mutable = isinstance(v, (ast.Dict, ast.List, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp)) or (
                isinstance(v, ast.Call)
                and dotted(v.func) in self._MUTABLE_CTORS)
            if not mutable:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        return out

    def check(self, ctx):
        mutables = self._module_mutables(ctx.tree)
        if not mutables:
            return
        targets = _thread_target_names(ctx.tree)
        if not targets:
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name not in targets:
                continue
            scan = _FunctionScanner(fn, set())
            local_names = _local_bindings(fn)
            for path, node, locked in scan.writes:
                root = path.split(".", 1)[0]
                if locked or root not in mutables or \
                        root in local_names:
                    continue
                yield node, (
                    f"thread target '{fn.name}' mutates module-level "
                    f"'{root}' with no lock held: wrap the write in a "
                    "lock (or move the state into an object that owns "
                    "one)")


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Names rebound locally (parameters or plain assignments), which
    therefore shadow any same-named module global — unless declared
    ``global``."""
    out: Set[str] = set()
    globals_: Set[str] = set()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        out.add(p.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            globals_.update(node.names)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out - globals_
