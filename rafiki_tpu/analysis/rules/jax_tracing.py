"""JAX tracing-hazard rules.

All three rules key off the module's *traced-function set*
(:func:`rafiki_tpu.analysis.astutil.traced_functions`): functions
decorated with / wrapped by ``jax.jit``/``pjit`` or handed to
``shard_map``. The same Python that is harmless eager becomes a
device round-trip, a silent recompile, or a
``ConcretizationTypeError`` once traced — which is why generic
linters never flag it.
"""

from __future__ import annotations

import ast

from ..astutil import JIT_NAMES, body_nodes, dotted, param_names
from ..engine import Rule, register

#: method calls that force the host to wait on (or copy from) the device
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
#: numpy entry points that pull a tracer/device buffer to host memory
_HOST_FUNCS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array", "np.copy", "numpy.copy",
}
#: builtins that concretize a tracer to a Python scalar
_SCALAR_BUILTINS = {"float", "int", "bool", "complex"}

#: annotations that mark a parameter as compile-time config, not data —
#: branching on those is resolved at trace time, not on a tracer
_STATIC_ANNOTATIONS = {"bool", "int", "str", "float"}


def _param_annotations(fn: ast.AST) -> dict:
    out = {}
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        ann = p.annotation
        if ann is not None:
            out[p.arg] = dotted(ann) or ""
    return out


@register
class JitHostSyncRule(Rule):
    id = "jax-host-sync"
    category = "jax"
    severity = "error"
    description = (
        "host-device sync inside a traced function: .item()/.tolist()/"
        ".block_until_ready()/np.asarray()/float() on a tracer blocks "
        "the device pipeline every step (or fails to trace at all)")

    def check(self, ctx):
        for fn, info in ctx.traced().items():
            params = set(param_names(fn)) - info.static_names
            for node in body_nodes(fn, skip=ctx.traced()):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _SYNC_METHODS:
                    yield node, (
                        f".{node.func.attr}() inside traced function "
                        f"'{fn.name}' (via {info.via}) forces a "
                        "host-device sync; compute on-device and pull "
                        "results after the traced call returns")
                    continue
                name = dotted(node.func)
                if name in _HOST_FUNCS:
                    yield node, (
                        f"{name}() inside traced function '{fn.name}' "
                        f"(via {info.via}) copies device values to host "
                        "numpy; use jnp inside traced code")
                elif (name in _SCALAR_BUILTINS and node.args
                      and isinstance(node.args[0], ast.Name)
                      and node.args[0].id in params):
                    yield node, (
                        f"{name}({node.args[0].id}) inside traced "
                        f"function '{fn.name}' concretizes a tracer to "
                        "a Python scalar; this raises under jit unless "
                        "the arg is static — mark it static_argnames or "
                        "keep it a jnp value")


@register
class TracerBranchRule(Rule):
    id = "jax-tracer-branch"
    category = "jax"
    severity = "error"
    description = (
        "Python if/while on a traced data argument: the branch runs on "
        "the TRACE, not per-element — raises ConcretizationTypeError "
        "or silently bakes one path into the compiled program")

    def check(self, ctx):
        for fn, info in ctx.traced().items():
            anns = _param_annotations(fn)
            data_params = {
                p for p in param_names(fn)
                if p not in info.static_names
                and anns.get(p, "") not in _STATIC_ANNOTATIONS
                # a parameter never annotated static but named like
                # config is still data as far as tracing is concerned —
                # no name-based exemptions here
            }
            for node in body_nodes(fn, skip=ctx.traced()):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                offender = self._scalar_param_test(node.test, data_params)
                if offender:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield node, (
                        f"`{kind}` on traced argument '{offender}' of "
                        f"'{fn.name}' (via {info.via}): under tracing "
                        "this branches on an abstract value — use "
                        "jnp.where/lax.cond/lax.select, or mark the "
                        "argument static")

    @staticmethod
    def _scalar_param_test(test: ast.AST, data_params) -> str:
        """Name of the offending parameter if the test is built purely
        from names/constants and touches a data parameter.

        Restricting to pure name/constant/compare tests keeps false
        positives near zero: ``if x.ndim == 3`` (shape — static under
        tracing) or ``if mask is None`` (identity on None) never match.
        """
        comparators = []
        if isinstance(test, ast.Name):
            comparators = [test]
        elif isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
                and isinstance(test.operand, ast.Name):
            comparators = [test.operand]
        elif isinstance(test, ast.Compare):
            # `x is None` / `x is not None` is an identity test on the
            # PYTHON value, legal and common for optional args
            if any(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
                return ""
            comparators = [n for n in [test.left] + test.comparators
                           if isinstance(n, ast.Name)]
            if not all(isinstance(n, (ast.Name, ast.Constant))
                       for n in [test.left] + test.comparators):
                return ""
        for name in comparators:
            if name.id in data_params:
                return name.id
        return ""


@register
class MissingDonationRule(Rule):
    id = "jax-missing-donation"
    category = "jax"
    severity = "warning"
    description = (
        "jit-compiled update function rebinds its first argument but "
        "declares no donate_argnums: the old buffer stays live across "
        "the step, doubling peak memory for the largest pytree")

    def check(self, ctx):
        for fn, info in ctx.traced().items():
            # donation is a jit/pjit concept; shard_map captures have
            # no donate_argnums to declare
            if info.donated or info.via not in JIT_NAMES:
                continue
            params = param_names(fn)
            if not params:
                continue
            first = params[0]
            if first in info.static_names:
                continue
            for node in body_nodes(fn, skip=ctx.traced()):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Name) and t.id == first:
                        yield node, (
                            f"'{fn.name}' rebinds its first argument "
                            f"'{first}' under jit without "
                            "donate_argnums=(0,): the pre-update buffer "
                            "and its replacement are both live at step "
                            "peak — donate the input to update in place")
                        break
                else:
                    continue
                break  # one finding per function is enough
