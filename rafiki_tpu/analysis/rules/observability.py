"""Observability-hygiene rules.

PR 6 replaced the repo's ad-hoc ``self.stats`` dicts with the
``rafiki_tpu.obs`` registry (locked StatsMaps, race-free snapshots,
Prometheus exposition). ``obs-unregistered-metric`` keeps the repo from
regressing: a bare ``something.stats[...] = ...`` write (or a fresh
``.stats = {...}`` dict literal) creates a counter the metrics plane
cannot see, whose reads race the writer, and whose name never reaches
``/metrics`` — exactly the drift this subsystem was built to end.
"""

from __future__ import annotations

import ast

from ..astutil import dotted
from ..engine import Rule, register


@register
class ObsUnregisteredMetricRule(Rule):
    id = "obs-unregistered-metric"
    category = "observability"
    severity = "error"
    description = (
        "ad-hoc `*.stats[...] = ...` counter write (or `.stats = {...}` "
        "dict literal) outside the obs registry: invisible to /metrics "
        "and racy to snapshot — use obs.StatsMap inc/set/max_set")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AugAssign):
                yield from self._check_subscript_target(node,
                                                        node.target)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    yield from self._check_subscript_target(node, t)
                    yield from self._check_dict_literal(node, t)

    @staticmethod
    def _is_stats_attr(expr) -> bool:
        """``<anything>.stats`` — the attribute spelling the repo's
        hand-rolled counter dicts all used. Bare local names
        (``stats[...]``) stay allowed: a function-local scratch dict is
        not a metrics surface."""
        return isinstance(expr, ast.Attribute) and expr.attr == "stats"

    def _check_subscript_target(self, stmt, target):
        if not isinstance(target, ast.Subscript):
            return  # plain rebinding (e.g. `self.stats = StatsMap(…)`)
        # peel chained subscripts: stats["a"]["b"] = ... still writes
        # through the stats mapping
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value
        if not self._is_stats_attr(base):
            return
        path = dotted(base) or "….stats"
        yield stmt, (
            f"'{path}[...] = ...' writes a counter behind the metrics "
            "plane's back (unregistered, racy to snapshot); make "
            f"'{path}' an obs.StatsMap and use "
            ".inc()/.set()/.max_set()")

    def _check_dict_literal(self, stmt, target):
        if not self._is_stats_attr(target):
            return
        if isinstance(stmt.value, (ast.Dict, ast.DictComp)):
            path = dotted(target) or "….stats"
            yield stmt, (
                f"'{path}' is created as a plain dict: its counters "
                "never reach /metrics and reads race writers — build "
                "an obs.StatsMap (and register it on the process's "
                "MetricsRegistry) instead")
