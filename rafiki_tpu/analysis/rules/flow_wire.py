"""Taint rule for wire-origin data reaching trusted sinks.

Hub payloads and HTTP bodies are parsed into plain dicts; nothing in
Python stops a field from flowing straight into an engine config, a
file path, or a subprocess argv. The repo's contract is that wire
fields pass a registered validator first (``normalize_slo``,
``check_kv_blob``, ``validate_override_keys``, or any
``validate_*``/``check_*``/``normalize_*``/``sanitize_*`` helper) —
this rule taints field reads off wire-named payloads and
``json.loads`` results and reports any flow that reaches a sink
unwashed, with the full path in the finding.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Tuple

from ..astutil import dotted
from ..dataflow import (FlowRule, TaintEngine, functions, has_source,
                        header_exprs, register_flow)

#: variables that denote wire-origin data by repo naming convention
_WIRE_NAME = re.compile(
    r"(?:^|_)(?:payload|body|msg|frame|wire|packet|request)s?$",
    re.IGNORECASE)

#: the registered validators (docs/linting.md "registered validator"
#: list) plus the conventional validator-shaped prefixes
_VALIDATORS = {"normalize_slo", "check_kv_blob",
               "validate_override_keys"}
_VALIDATOR_PREFIX = ("validate_", "check_", "normalize_", "sanitize_",
                     "parse_")
#: numeric casts produce a value the sink can bound-check trivially
_CAST_FUNCS = {"int", "float", "bool"}

_CONFIG_TARGET = re.compile(r"(?:^|_)(?:config|cfg|options?)$",
                            re.IGNORECASE)
_SUBPROCESS = {"subprocess.run", "subprocess.Popen",
               "subprocess.check_call", "subprocess.check_output",
               "os.system", "os.execv", "os.execvp"}
_PATH_FUNCS = {"open", "Path", "pathlib.Path", "os.remove",
               "os.unlink", "os.makedirs", "shutil.rmtree"}


def _wire_base(node: ast.AST) -> Optional[str]:
    """The wire-named variable a subscript/.get chain hangs off."""
    name = dotted(node)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    return name if _WIRE_NAME.search(last) else None


#: reads that mark a json.loads argument as wire-origin (vs a local
#: config file, whose json.load/loads is trusted operator input)
_RECV_ATTRS = {"read", "recv", "recv_bytes", "recv_json"}


def _wire_read(node: ast.AST) -> bool:
    if isinstance(node, (ast.Name, ast.Attribute)):
        return _wire_base(node) is not None
    if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute):
        return node.func.attr in _RECV_ATTRS
    return False


def _wire_source(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        # json.loads of wire-named data or a socket/stream read; a
        # plain json.load(f) of a local config file is NOT wire input
        if name == "json.loads":
            for arg in node.args:
                if any(_wire_read(sub) for sub in ast.walk(arg)):
                    return "wire payload parsed here (json.loads)"
            return None
        # payload.get("field")
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get":
            base = _wire_base(node.func.value)
            if base:
                return f"field read from wire payload '{base}'"
        return None
    if isinstance(node, ast.Subscript):
        base = _wire_base(node.value)
        if base:
            return f"field read from wire payload '{base}'"
    return None


def _validator(call: ast.Call) -> bool:
    name = (dotted(call.func) or "").rsplit(".", 1)[-1]
    if name in _VALIDATORS or name in _CAST_FUNCS:
        return True
    return name.startswith(_VALIDATOR_PREFIX)


@register_flow
class UnvalidatedWireInputRule(FlowRule):
    id = "unvalidated-wire-input"
    category = "robustness"
    severity = "error"
    description = (
        "a field read off a wire payload (hub message / HTTP body / "
        "json.loads result) reaches engine or worker config, a file "
        "path, or subprocess args without passing a registered "
        "validator — wash it through normalize_slo / check_kv_blob / "
        "validate_override_keys (or a validate_*/check_* helper) "
        "first")
    sources = (
        "subscript or .get() reads off wire-named variables "
        "(payload/body/msg/frame/wire/packet/request)",
        "json.loads() of wire-named data or .read()/.recv() results "
        "(json.load of a local config file is trusted)",
    )
    sinks = (
        "subprocess.run/Popen/check_* and os.system/exec* arguments",
        "open()/Path()/os.remove()-style file-path arguments",
        "constructors named *Config/*Engine/*Worker/*Spec",
        "assignments to config/cfg/options-named targets",
    )
    sanitizers = (
        "registered validators: normalize_slo, check_kv_blob, "
        "validate_override_keys",
        "validate_*/check_*/normalize_*/sanitize_*/parse_* helpers",
        "numeric casts (int/float/bool)",
    )
    example = (
        "def on_override(self, payload):\n"
        "    path = payload['snapshot_path']     # wire field\n"
        "    subprocess.run(['cp', path, self.dir])  # unwashed argv\n")

    _CTOR = re.compile(r"(?:Config|Engine|Worker|Spec)$")

    def check(self, ctx) -> Iterator[Tuple[ast.AST, str, tuple]]:
        for fn, cfg in functions(ctx):
            if not has_source(fn, _wire_source):
                continue
            eng = TaintEngine(cfg, _wire_source, _validator).run()
            for block, idx, stmt in cfg.statements():
                yield from self._check_stmt(eng, stmt)

    def _check_stmt(self, eng, stmt):
        state = eng.state_before(stmt)
        # sink: config-named assignment targets
        if isinstance(stmt, ast.Assign):
            targets = [t for t in stmt.targets
                       if self._config_target(t)]
            if targets:
                taint = eng.eval(stmt.value, state)
                if taint is not None:
                    name = dotted(targets[0]) or "config"
                    yield stmt, self._msg(f"config value '{name}'"), \
                        self.trace_from_taint(
                            taint, stmt,
                            f"stored into config '{name}' here")
        for part in header_exprs(stmt):
            for node in ast.walk(part):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func) or ""
                sink = None
                if name in _SUBPROCESS:
                    sink = f"subprocess args ({name})"
                elif name in _PATH_FUNCS:
                    sink = f"a file path ({name})"
                elif self._CTOR.search(name.rsplit(".", 1)[-1]):
                    sink = f"'{name}(...)' construction"
                if sink is None:
                    continue
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    taint = eng.eval(arg, state)
                    if taint is not None:
                        yield arg, self._msg(sink), \
                            self.trace_from_taint(
                                taint, arg, f"reaches {sink} here")
                        break  # one finding per call is enough

    @staticmethod
    def _config_target(target: ast.AST) -> bool:
        if isinstance(target, ast.Name):
            return bool(_CONFIG_TARGET.search(target.id))
        if isinstance(target, ast.Attribute):
            return bool(_CONFIG_TARGET.search(target.attr))
        return False

    @staticmethod
    def _msg(sink: str) -> str:
        return (f"unvalidated wire-payload data reaches {sink}: a "
                f"malformed or hostile field flows straight into a "
                f"trusted surface — wash it through a registered "
                f"validator (normalize_slo / check_kv_blob / "
                f"validate_override_keys or a validate_*/check_* "
                f"helper) first")
