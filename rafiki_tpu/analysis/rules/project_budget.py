"""budget-key-parity: budget keys and worker config must stay a closed
loop.

A budget key lives in four places: the admin create API validates it
(``budget.get("KV_PAGES")``), the services manager turns it into a
worker config entry (``cfg["kv_pages"] = ...``), the spawned service
consumes that entry (``cfg.get("kv_pages")``), and the operator docs
table explains it. Each hop is a different file — usually a different
process, with the config crossing as JSON — so nothing type-checks the
chain, and the observed drift modes are all silent: a validated key the
docs never mention (operators can't know it exists), a config entry
produced but consumed nowhere (dead knob, reads as supported), and a
required config read no producer writes (KeyError at spawn, or a
``None`` default silently winning forever).

The contract edge is recovered from the spawn calls themselves:
``self._spawn("rafiki_tpu.worker.inference", cfg, ...)`` names the
consumer module as a string constant, so the rule knows exactly which
modules' ``cfg`` reads belong to the admin-produced config — reads of
unrelated ``cfg`` dicts elsewhere (harness configs, server settings)
are out of contract and never flagged.

Three sub-checks:

- **docs parity** — every SCREAMING_CASE key read off a ``*budget``
  receiver must appear backticked somewhere in the collected markdown;
- **dead knobs** — keys produced (dict-literal ``_spawn`` args,
  stores into ``cfg``/``*_cfg`` dicts in budget-handling modules) but
  consumed by no spawn-target module;
- **missing producers** — *required* reads in spawn-target modules
  (``cfg["k"]`` subscripts and defaultless ``cfg.get("k")``) whose key
  no producer writes. Reads with an explicit default
  (``cfg.get("k", 4)``) declare the key optional and are exempt — that
  is the repo's idiom for standalone/manual deployment knobs.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from ..astutil import dotted
from ..project import ProjectContext, ProjectRule, register_project

_BUDGET_KEY_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
_CFG_KEY_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_BACKTICK_RE = re.compile(r"`([^`]+)`")


def _receiver_is_budget(node: ast.AST) -> bool:
    path = dotted(node)
    if not path:
        return False
    last = path.rsplit(".", 1)[-1]
    return last == "budget" or last.endswith("_budget")


def _receiver_is_cfg(node: ast.AST) -> bool:
    path = dotted(node)
    if not path:
        return False
    last = path.rsplit(".", 1)[-1]
    return last in ("cfg", "config") or \
        last.endswith(("_cfg", "_config"))


def _cfg_name(name: str) -> bool:
    return name in ("cfg", "config") or \
        name.endswith(("_cfg", "_config"))


def _const_str(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@register_project
class BudgetKeyParityRule(ProjectRule):
    id = "budget-key-parity"
    category = "robustness"
    severity = "error"
    description = (
        "budget/config contract drift: an admin-validated budget key "
        "with no docs mention, a config key produced for a spawned "
        "service that never reads it (dead knob), or a required read "
        "in a spawned service that no producer writes")

    def check(self, project: ProjectContext):
        budget_sites: Dict[str, Tuple[str, int]] = {}
        budget_modules: Set[str] = set()
        for mod, ctx in sorted(project.modules.items()):
            for node in ast.walk(ctx.tree):
                for key in self._budget_keys(node):
                    budget_sites.setdefault(
                        key, (ctx.path, node.lineno))
                    budget_modules.add(mod)

        produced: Dict[str, Tuple[str, int]] = {}
        targets: Set[str] = set()
        for mod in sorted(budget_modules):
            ctx = project.modules[mod]
            for node in ast.walk(ctx.tree):
                for key, line in self._produced_keys(node):
                    produced.setdefault(key, (ctx.path, line))
                targets.update(self._spawn_targets(node))

        consumed: Dict[str, Tuple[str, int]] = {}
        required: Dict[str, Tuple[str, int]] = {}
        for mod in sorted(targets):
            if mod not in project.modules:
                continue
            ctx = project.modules[mod]
            for node in ast.walk(ctx.tree):
                for key, line, req in self._consumed_keys(node):
                    consumed.setdefault(key, (ctx.path, line))
                    if req:
                        required.setdefault(key, (ctx.path, line))

        yield from self._docs_parity(project, budget_sites)
        if not targets:
            return  # no spawn edge in this tree — config checks moot
        for key, (path, line) in sorted(produced.items()):
            if key not in consumed:
                yield (path, line, 0, (
                    f"config key '{key}' is produced here but no "
                    "spawned service module ever reads it — a dead "
                    "knob that looks supported; consume it or stop "
                    "producing it"))
        for key, (path, line) in sorted(required.items()):
            if key not in produced:
                yield (path, line, 0, (
                    f"config key '{key}' is required here (read with "
                    "no default) but no budget-handling module ever "
                    "produces it — this spawn path cannot work; "
                    "produce the key or give the read an explicit "
                    "default"))

    # ---- extraction ----

    @staticmethod
    def _budget_keys(node: ast.AST):
        key = None
        if isinstance(node, ast.Subscript) and \
                _receiver_is_budget(node.value):
            key = _const_str(node.slice)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                _receiver_is_budget(node.func.value):
            key = _const_str(node.args[0])
        elif isinstance(node, ast.Compare) and \
                len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                _receiver_is_budget(node.comparators[0]):
            key = _const_str(node.left)
        if key is not None and _BUDGET_KEY_RE.match(key):
            yield key

    @staticmethod
    def _consumed_keys(node: ast.AST):
        """(key, line, required?) reads; subscripts and defaultless
        ``.get`` are required, ``.get(k, default)`` is optional."""
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and \
                _receiver_is_cfg(node.value):
            key = _const_str(node.slice)
            if key is not None and _CFG_KEY_RE.match(key):
                yield key, node.lineno, True
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                _receiver_is_cfg(node.func.value):
            key = _const_str(node.args[0])
            if key is not None and _CFG_KEY_RE.match(key):
                yield key, node.lineno, len(node.args) < 2

    @classmethod
    def _produced_keys(cls, node: ast.AST):
        # cfg["k"] = ... subscript stores
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        _receiver_is_cfg(t.value):
                    key = _const_str(t.slice)
                    if key is not None and _CFG_KEY_RE.match(key):
                        yield key, t.lineno
                elif isinstance(t, ast.Name) and _cfg_name(t.id) and \
                        isinstance(node.value, ast.Dict):
                    yield from cls._dict_keys(node.value)
        # pred_cfg: Dict[str, Any] = {...} — annotated form of the same
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                _cfg_name(node.target.id) and \
                isinstance(node.value, ast.Dict):
            yield from cls._dict_keys(node.value)
        # dict literal handed straight to a spawn call
        elif isinstance(node, ast.Call) and cls._is_spawn(node):
            for arg in list(node.args) + \
                    [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Dict):
                    yield from cls._dict_keys(arg)

    @staticmethod
    def _is_spawn(node: ast.Call) -> bool:
        return (dotted(node.func) or "").rsplit(".", 1)[-1] \
            in ("_spawn", "spawn")

    @classmethod
    def _spawn_targets(cls, node: ast.AST) -> List[str]:
        """Module names named by constant first args of spawn calls."""
        if not (isinstance(node, ast.Call) and cls._is_spawn(node)
                and node.args):
            return []
        mod = _const_str(node.args[0])
        if mod is not None and "." in mod and \
                re.fullmatch(r"[\w.]+", mod):
            return [mod]
        return []

    @staticmethod
    def _dict_keys(node: ast.Dict):
        for k in node.keys:
            key = _const_str(k) if k is not None else None
            if key is not None and _CFG_KEY_RE.match(key):
                yield key, k.lineno

    # ---- docs ----

    def _docs_parity(self, project: ProjectContext, budget_sites):
        docs = project.md_resources()
        if not docs:
            return  # fixture trees without docs check config only
        mentioned: Set[str] = set()
        for res in docs:
            for line in res.lines:
                mentioned.update(_BACKTICK_RE.findall(line))
        for key, (path, line) in sorted(budget_sites.items()):
            if key not in mentioned:
                yield (path, line, 0, (
                    f"budget key '{key}' is read at the admin API but "
                    "documented nowhere (no backticked mention in any "
                    "collected .md) — add it to the operator docs"))
