"""Built-in lint rules; importing this package registers them all.

One module per hazard category (mirrors ``docs/linting.md``):

- :mod:`jax_tracing` — hazards that only exist under ``jax.jit`` /
  ``pjit`` / ``shard_map`` tracing.
- :mod:`robustness` — error-handling and library-internals hazards.
- :mod:`observability` — counters written behind the metrics plane's
  back.
- :mod:`serving` — decode-loop hot-path hazards (blocking transfers).

Project-scope rules (``lint --project``), one module per contract:

- :mod:`project_locks` — interprocedural lock-order cycles and locks
  held across blocking calls.
- :mod:`project_hub` — hub verb parity across server/client/interface/
  decorator layers.
- :mod:`project_metrics` — metric catalog drift across code, docs, and
  dashboard.
- :mod:`project_budget` — budget-key / worker-config / docs parity.
- :mod:`project_spans` — span streams that can never terminate.

Thread-model rules (``lint --project``, tagged ``[threads:...]``;
see :mod:`rafiki_tpu.analysis.threads`):

- :mod:`project_threads` — interprocedural data races, unlocked
  read-modify-writes, and non-daemon threads with no join on the
  teardown path. These supersede the retired per-module
  ``inconsistent-lock`` / ``thread-unlocked-global`` rules (their
  noqa ids still apply via aliasing; :mod:`concurrency` keeps the
  shared lock/mutator vocabulary).

Flow-scope rules (path-sensitive, CFG + dataflow; see
:mod:`rafiki_tpu.analysis.dataflow`), run in the per-file pass:

- :mod:`flow_locks` — a manual ``.acquire()`` missing its release on
  some path.
- :mod:`flow_jit` — use-after-donate reads and runtime-varying values
  in static jit args.
- :mod:`flow_clock` — real wall-clock taint into deadlines (replaces
  the name-heuristic ``wall-clock-deadline``).
- :mod:`flow_wire` — wire-payload fields reaching config/paths/argv
  without a registered validator.
"""

from . import (concurrency, flow_clock, flow_jit,  # noqa: F401
               flow_locks, flow_wire, jax_tracing, observability,
               project_budget, project_hub, project_locks,
               project_metrics, project_spans, project_threads,
               robustness, serving)
