"""Built-in lint rules; importing this package registers them all.

One module per hazard category (mirrors ``docs/linting.md``):

- :mod:`jax_tracing` — hazards that only exist under ``jax.jit`` /
  ``pjit`` / ``shard_map`` tracing.
- :mod:`concurrency` — shared-state hazards across the serving/worker
  threads.
- :mod:`robustness` — error-handling and library-internals hazards.
- :mod:`observability` — counters written behind the metrics plane's
  back.
- :mod:`serving` — decode-loop hot-path hazards (blocking transfers).

Project-scope rules (``lint --project``), one module per contract:

- :mod:`project_locks` — interprocedural lock-order cycles and locks
  held across blocking calls.
- :mod:`project_hub` — hub verb parity across server/client/interface/
  decorator layers.
- :mod:`project_metrics` — metric catalog drift across code, docs, and
  dashboard.
- :mod:`project_budget` — budget-key / worker-config / docs parity.
- :mod:`project_spans` — span streams that can never terminate.
"""

from . import (concurrency, jax_tracing, observability,  # noqa: F401
               project_budget, project_hub, project_locks,
               project_metrics, project_spans, robustness, serving)
