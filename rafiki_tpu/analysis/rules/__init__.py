"""Built-in lint rules; importing this package registers them all.

One module per hazard category (mirrors ``docs/linting.md``):

- :mod:`jax_tracing` — hazards that only exist under ``jax.jit`` /
  ``pjit`` / ``shard_map`` tracing.
- :mod:`concurrency` — shared-state hazards across the serving/worker
  threads.
- :mod:`robustness` — error-handling and library-internals hazards.
- :mod:`observability` — counters written behind the metrics plane's
  back.
- :mod:`serving` — decode-loop hot-path hazards (blocking transfers).

Project-scope rules (``lint --project``), one module per contract:

- :mod:`project_locks` — interprocedural lock-order cycles and locks
  held across blocking calls.
- :mod:`project_hub` — hub verb parity across server/client/interface/
  decorator layers.
- :mod:`project_metrics` — metric catalog drift across code, docs, and
  dashboard.
- :mod:`project_budget` — budget-key / worker-config / docs parity.
- :mod:`project_spans` — span streams that can never terminate.

Flow-scope rules (path-sensitive, CFG + dataflow; see
:mod:`rafiki_tpu.analysis.dataflow`), run in the per-file pass:

- :mod:`flow_locks` — a manual ``.acquire()`` missing its release on
  some path.
- :mod:`flow_jit` — use-after-donate reads and runtime-varying values
  in static jit args.
- :mod:`flow_clock` — real wall-clock taint into deadlines (replaces
  the name-heuristic ``wall-clock-deadline``).
- :mod:`flow_wire` — wire-payload fields reaching config/paths/argv
  without a registered validator.
"""

from . import (concurrency, flow_clock, flow_jit,  # noqa: F401
               flow_locks, flow_wire, jax_tracing, observability,
               project_budget, project_hub, project_locks,
               project_metrics, project_spans, robustness, serving)
