"""Built-in lint rules; importing this package registers them all.

One module per hazard category (mirrors ``docs/linting.md``):

- :mod:`jax_tracing` — hazards that only exist under ``jax.jit`` /
  ``pjit`` / ``shard_map`` tracing.
- :mod:`concurrency` — shared-state hazards across the serving/worker
  threads.
- :mod:`robustness` — error-handling and library-internals hazards.
- :mod:`observability` — counters written behind the metrics plane's
  back.
- :mod:`serving` — decode-loop hot-path hazards (blocking transfers).
"""

from . import (concurrency, jax_tracing, observability,  # noqa: F401
               robustness, serving)
