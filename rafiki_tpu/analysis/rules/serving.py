"""Serving hot-path rules.

The decode engine's ``step()`` is the per-token hot loop: every
generated token of every live stream goes through it, so one
synchronous device→host transfer there stalls the WHOLE batch — not
one request — and repeats per step. The host-KV-tier design keeps
those transfers on a dedicated tier thread
(``serving/kv_tier.py``); the prefetcher's async staging is the
sanctioned idiom, and this rule exists so a future edit can't quietly
reintroduce a blocking transfer into the loop.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set

from ..engine import Rule, register

#: dotted call names that force a synchronous device→host transfer
_DEVICE_GET = {"jax.device_get"}
#: bare ``np.asarray(x)`` / ``np.array(x)`` spellings; with a second
#: (dtype) argument the call is read as a host-side cast of host data
#: — the d2h-sync idiom is the single-argument form on a device array
_NP_PULLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
             "onp.asarray", "onp.array"}


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _self_calls(fn: ast.FunctionDef) -> Set[str]:
    """Names of ``self.<m>(...)`` methods the function calls."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self":
            out.add(node.func.attr)
    return out


@register
class BlockingTransferInDecodeLoopRule(Rule):
    id = "blocking-transfer-in-decode-loop"
    category = "serving"
    severity = "error"
    description = (
        "synchronous device->host transfer (jax.device_get / "
        ".block_until_ready() / bare np.asarray(device_array)) inside "
        "a decode engine's step() loop: one blocked transfer stalls "
        "every live stream's next token, every step — move it to the "
        "host-tier transfer thread (the prefetcher's async staging is "
        "the sanctioned idiom)")

    def check(self, ctx):
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods: Dict[str, ast.FunctionDef] = {
                n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))}
            if "step" not in methods or "submit" not in methods:
                # only decode-engine-shaped classes have a step LOOP
                # (continuous batching: submit feeds it, step drives
                # it); a lone step() elsewhere is not a hot loop
                continue
            for name in self._reachable(methods):
                yield from self._scan(methods[name], name)

    @staticmethod
    def _reachable(methods: Dict[str, ast.FunctionDef]
                   ) -> Iterable[str]:
        """Methods transitively reachable from ``step`` via
        ``self.X()`` calls — the step loop's actual extent. Methods
        only callable outside the loop (register_prefix, reset, poll)
        are deliberately out of scope: blocking there costs one call,
        not every token."""
        seen = {"step"}
        frontier = ["step"]
        while frontier:
            m = frontier.pop()
            for callee in _self_calls(methods[m]):
                if callee in methods and callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen

    def _scan(self, fn: ast.FunctionDef, name: str):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "block_until_ready":
                yield node, (
                    f".block_until_ready() in '{name}' (reachable "
                    "from step()): blocks the decode loop on device "
                    "completion — dispatch and move on, or hand the "
                    "wait to the tier thread")
                continue
            dn = _dotted(node.func)
            if dn in _DEVICE_GET or dn.endswith(".device_get"):
                yield node, (
                    f"{dn}() in '{name}' (reachable from step()): a "
                    "synchronous device->host copy in the decode "
                    "loop — queue it on the host-tier transfer "
                    "thread instead")
            elif dn in _NP_PULLS and len(node.args) == 1 \
                    and not node.keywords:
                yield node, (
                    f"bare {dn}(x) in '{name}' (reachable from "
                    "step()): if x is a device array this is a "
                    "synchronous d2h pull stalling every live "
                    "stream — use the tier thread (or, for host "
                    "data, pass an explicit dtype to mark it a "
                    "host-side cast)")
