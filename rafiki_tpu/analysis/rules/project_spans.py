"""span-lifecycle: every span stream must be able to end.

Trace spans are the only request-scoped truth the stack has — the
predictor, the engine hook, and the chaos harnesses all emit them via
a ``span_sink`` / ``_span`` / ``add_span`` call. A component that emits
progress events (``admitted``, ``prefill``, ``first_token``) but never
a *terminal* one (``done`` / ``expired`` / ``rejected`` / ``preempted``
/ ``errored``) produces traces that all look permanently in-flight:
dashboards count them as live, TTL sweepers can't distinguish leaked
from slow, and every debugging session starts with "is it stuck or did
we just never emit the end?".

The rule groups span emissions by component (the enclosing class, or
the module for free functions) and flags any component whose emitted
event set contains no terminal. Matching is by constant event name;
``*_done`` / ``*_errored`` style names count as terminal (the train
worker's ``trial_done``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..astutil import dotted
from ..project import ProjectContext, ProjectRule, register_project

_TERMINALS = {"done", "expired", "rejected", "preempted", "errored"}
_TERMINAL_SUFFIXES = ("_done", "_expired", "_rejected", "_errored")


def _emitted_event(node: ast.AST):
    """Constant event name of a span emission call, else None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted(node.func)
    if not name:
        return None
    last = name.rsplit(".", 1)[-1]
    arg = None
    if last in ("span_sink", "_span"):
        arg = node.args[0] if node.args else None
    elif last == "add_span":
        arg = node.args[1] if len(node.args) > 1 else None
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


def _is_terminal(event: str) -> bool:
    return event in _TERMINALS or event.endswith(_TERMINAL_SUFFIXES)


@register_project
class SpanLifecycleRule(ProjectRule):
    id = "span-lifecycle"
    category = "observability"
    severity = "error"
    description = (
        "a component emits trace spans but never a terminal event "
        "(done/expired/rejected/preempted/errored): every trace it "
        "produces looks permanently in-flight")

    def check(self, project: ProjectContext):
        # component name -> [(event, ctx, node)]
        comps: Dict[str, List[Tuple[str, object, ast.AST]]] = {}
        for mod, ctx in sorted(project.modules.items()):
            class_nodes = [n for n in ast.walk(ctx.tree)
                           if isinstance(n, ast.ClassDef)]
            in_class = set()
            for cls in class_nodes:
                for node in ast.walk(cls):
                    in_class.add(node)
                    ev = _emitted_event(node)
                    if ev is not None:
                        comps.setdefault(f"{mod}:{cls.name}",
                                         []).append((ev, ctx, node))
            for node in ast.walk(ctx.tree):
                if node in in_class:
                    continue
                ev = _emitted_event(node)
                if ev is not None:
                    comps.setdefault(mod, []).append((ev, ctx, node))
        for comp, emissions in sorted(comps.items()):
            events = {ev for ev, _, _ in emissions}
            if any(_is_terminal(ev) for ev in events):
                continue
            ev, ctx, node = emissions[0]
            yield self.at(ctx, node, (
                f"'{comp.rsplit(':', 1)[-1]}' emits span event(s) "
                f"{', '.join(sorted(events))} but never a terminal "
                "event (done/expired/rejected/preempted/errored) — "
                "every trace from this component looks permanently "
                "in-flight; emit a terminal on each exit path"))
