"""Robustness-hazard rules.

``silent-except`` is the rule this whole subsystem was built around:
ADVICE.md's admission-control finding was a broad ``except Exception:``
whose body was a bare ``return`` — a single line that silently disabled
fleet-wide OOM protection, with zero signal anywhere. ``library-
internals`` guards the other documented hazard: code that reaches into
CPython/stdlib private attributes works until a point release, then
degrades in whatever way the surrounding code happens to allow.
"""

from __future__ import annotations

import ast
from typing import Set

from ..astutil import attr_depth, chain_root, dotted
from ..engine import Rule, register

#: broad exception types where swallowing is a hazard; a narrow
#: ``except KeyError: use_default()`` is normal control flow.
_BROAD = {"Exception", "BaseException"}

def _handles_visibly(handler: ast.ExceptHandler) -> bool:
    """Does the handler body do ANYTHING observable with the failure?

    Re-raising, logging, or in fact calling any function at all counts:
    a body that invokes a fallback path is handling, not swallowing.
    The hazard this rule exists for is the handler whose body is pure
    control flow (``pass`` / ``return`` / constant assignment) — the
    failure leaves no trace anywhere.
    """
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Call)):
            return True
    return False


def _uses_exception_var(handler: ast.ExceptHandler) -> bool:
    """``except Exception as e`` where the body actually reads ``e``:
    the error is being inspected/propagated somehow, not swallowed."""
    if handler.name is None:
        return False
    for node in ast.walk(handler):
        if isinstance(node, ast.Name) and node.id == handler.name and \
                isinstance(node.ctx, ast.Load):
            return True
    return False


@register
class SilentExceptRule(Rule):
    id = "silent-except"
    category = "robustness"
    severity = "error"
    description = (
        "broad except whose body neither re-raises, logs, nor reads "
        "the exception: failures vanish without a trace (the exact "
        "shape of the ADVICE.md admission-control bug)")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            type_name = self._broad_name(node.type)
            if node.type is not None and type_name is None:
                continue  # narrow except: normal control flow
            if _handles_visibly(node) or _uses_exception_var(node):
                continue
            shown = type_name or "bare except"
            yield node, (
                f"except {shown}: swallows every error with no trace "
                "— log it (logging.warning with exc_info / repr(e)), "
                "re-raise, call a fallback, or narrow the exception "
                "type")

    @staticmethod
    def _broad_name(type_node):
        """The broad type's name if this handler is broad, else None.

        ``except:`` -> "bare except"; ``except (ValueError, Exception)``
        is broad because ONE member is; ``except (KeyError, OSError)``
        is narrow and returns None.
        """
        if type_node is None:
            return "bare except"
        elts = type_node.elts if isinstance(type_node, ast.Tuple) \
            else [type_node]
        for elt in elts:
            name = dotted(elt)
            if name in _BROAD:
                return name
        return None


@register
class LibraryInternalsRule(Rule):
    id = "library-internals"
    category = "robustness"
    severity = "warning"
    description = (
        "reaching into another object's private internals (deep "
        "`_attr` chains / getattr(obj, '_attr')): works until the "
        "library refactors — keep a behavioral fallback next to it "
        "and suppress the finding to document the contract")

    #: roots whose privates are OUR OWN: accessing self._x (or a
    #: module-local conventionally-private helper) is normal Python.
    _OWN_ROOTS: Set[str] = {"self", "cls"}

    def check(self, ctx):
        # names DEFINED in this module (functions/classes): their
        # private attributes are ours, not a library's
        own = {n.name for n in ctx.tree.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef))}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                yield from self._check_attr(node, own)
            elif isinstance(node, ast.Call):
                yield from self._check_getattr(node, own)

    def _check_attr(self, node: ast.Attribute, own):
        attr = node.attr
        if not attr.startswith("_") or attr.startswith("__"):
            return
        # only DEEP chains (a.b._c and beyond): obj._x on a local name
        # is usually package-internal access; two-plus hops means we
        # are navigating someone else's object graph
        if attr_depth(node) < 3:
            return
        root = chain_root(node)
        if isinstance(root, ast.Name) and (root.id in self._OWN_ROOTS
                                           or root.id in own):
            return
        path = dotted(node) or f"...{attr}"
        yield node, (
            f"'{path}' navigates a foreign object's private internals; "
            "an upstream refactor breaks this silently — pair it with "
            "a fallback and suppress with `# rafiki: noqa"
            "[library-internals]` to record the contract")

    def _check_getattr(self, node: ast.Call, own):
        if not (isinstance(node.func, ast.Name)
                and node.func.id == "getattr" and len(node.args) >= 2):
            return
        name_arg = node.args[1]
        if not (isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)):
            return
        attr = name_arg.value
        if not attr.startswith("_") or attr.startswith("__"):
            return
        base = node.args[0]
        root = chain_root(base) if isinstance(
            base, ast.Attribute) else base
        if isinstance(root, ast.Name) and (root.id in self._OWN_ROOTS
                                           or root.id in own):
            return
        yield node, (
            f"getattr(..., {attr!r}) probes a private attribute of a "
            "foreign object; an upstream refactor breaks this silently "
            "— pair it with a fallback and suppress with `# rafiki: "
            "noqa[library-internals]` to record the contract")
