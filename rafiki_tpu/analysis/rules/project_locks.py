"""lock-order-cycle: interprocedural lock-acquisition analysis.

The per-file ``inconsistent-lock`` rule answers "is this attr written
with the lock held"; it cannot answer the question that took three
review passes of the tier-thread PR: *given everything anybody calls
while holding a lock, can two threads arrive at the same pair of locks
in opposite orders?* That needs the project view:

1. name every lock in the project (``module:Class.attr`` for
   ``self.x = threading.Lock()``, ``module:name`` for module globals;
   ``threading.Condition(self._lock)`` ALIASES the wrapped lock — the
   kv-tier and mesh pattern);
2. scan every function for ``with <lock>:`` scopes and ``.acquire()``
   sites, tracking the held set through nesting;
3. propagate through the call graph: calling ``f`` while holding L
   charges L -> M for every M that ``f`` (transitively) acquires;
4. flag cycles in the resulting acquired-while-holding graph, and —
   the softer sibling hazard — locks held across known-blocking calls
   (``time.sleep``, socket ``recv``/``accept``, ``subprocess.run``),
   which turn "brief critical section" into "everyone stalls behind a
   sleeping thread". ``Condition.wait``/``wait_for`` are exempt: they
   release the wrapped lock while waiting.

Heuristics are deliberately conservative: a ``with`` whose context we
cannot resolve to a named project lock contributes nothing, and a bare
``.acquire()`` records an acquisition EVENT (for ordering edges) but
does not extend the held scope — we don't guess where the matching
``release()`` is.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..astutil import dotted
from ..engine import ModuleContext
from ..project import (FunctionInfo, ProjectContext, ProjectRule,
                       register_project)
from .concurrency import _LOCK_CTORS

#: calls that block the calling thread for unbounded / wall-clock time.
#: Exact dotted names…
_BLOCKING_EXACT = {
    "time.sleep", "subprocess.run", "subprocess.check_output",
    "subprocess.check_call", "subprocess.call", "select.select",
}
#: …and attribute suffixes (socket/pipe receivers). ``.join``/``.get``
#: are NOT here: str.join and dict.get would drown the signal.
_BLOCKING_ATTRS = {"recv", "accept", "communicate", "recv_into"}

#: Condition methods that RELEASE the wrapped lock while waiting
_CV_RELEASING = {"wait", "wait_for", "notify", "notify_all"}


class _LockNames:
    """Lock identity tables for one project."""

    def __init__(self, project: ProjectContext):
        self.project = project
        # class qualname -> {attr -> canonical attr on same class}
        self.class_locks: Dict[str, Dict[str, str]] = {}
        # module -> {global name}
        self.module_locks: Dict[str, Set[str]] = {}
        for q, info in project.classes.items():
            self.class_locks[q] = self._scan_class(info.node)
        for mod, ctx in project.modules.items():
            globs: Set[str] = set()
            for node in ctx.tree.body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        dotted(node.value.func) in _LOCK_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            globs.add(t.id)
            self.module_locks[mod] = globs

    @staticmethod
    def _scan_class(cls: ast.ClassDef) -> Dict[str, str]:
        attrs: Dict[str, str] = {}
        alias: Dict[str, str] = {}
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            ctor = dotted(node.value.func)
            if ctor not in _LOCK_CTORS:
                continue
            targets = [dotted(t) for t in node.targets]
            names = [t[5:] for t in targets
                     if t and t.startswith("self.") and
                     t.count(".") == 1]
            if not names:
                continue
            # Condition(self.X) aliases the wrapped lock
            wrapped = None
            if ctor.rsplit(".", 1)[-1] == "Condition" and \
                    node.value.args:
                arg = dotted(node.value.args[0])
                if arg and arg.startswith("self.") and \
                        arg.count(".") == 1:
                    wrapped = arg[5:]
            for name in names:
                attrs[name] = name
                if wrapped:
                    alias[name] = wrapped
        # collapse alias chains (bounded — chains are length 1 in
        # practice, but don't loop forever on a self-alias)
        for name, target in alias.items():
            seen = {name}
            while target in alias and target not in seen:
                seen.add(target)
                target = alias[target]
            if target in attrs:
                attrs[name] = target
        return attrs

    def resolve(self, fn: FunctionInfo,
                expr: ast.AST) -> Optional[str]:
        """Lock id for a with-context / acquire receiver, or None."""
        path = dotted(expr)
        if not path:
            return None
        if path.startswith("self.") and path.count(".") == 1 and fn.cls:
            attr = path[5:]
            for c in self.project.class_mro(fn.cls):
                table = self.class_locks.get(c.qualname, {})
                if attr in table:
                    return f"{c.qualname}.{table[attr]}"
            return None
        if "." not in path and \
                path in self.module_locks.get(fn.module, ()):
            return f"{fn.module}:{path}"
        return None


class _FnSummary:
    """What one function does with locks, from a single body scan."""

    def __init__(self):
        #: (lock id, node, held-at-acquire tuple)
        self.acquires: List[Tuple[str, ast.AST, Tuple[str, ...]]] = []
        #: (call node, dotted name, held tuple)
        self.calls: List[Tuple[ast.Call, str, Tuple[str, ...]]] = []


def _scan_function(fn: FunctionInfo, names: _LockNames) -> _FnSummary:
    out = _FnSummary()
    for stmt in fn.node.body:
        _scan_node(fn, stmt, (), names, out)
    return out


def _scan_node(fn: FunctionInfo, node: ast.AST, held: Tuple[str, ...],
               names: _LockNames, out: _FnSummary) -> None:
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda, ast.ClassDef)):
        return  # nested defs run later, on their own stack
    if isinstance(node, (ast.With, ast.AsyncWith)):
        inner = held
        for item in node.items:
            # the context expression itself evaluates under the OUTER
            # held set
            _scan_node(fn, item.context_expr, held, names, out)
            lid = names.resolve(fn, item.context_expr)
            if lid is not None:
                out.acquires.append((lid, node, inner))
                if lid not in inner:
                    inner = inner + (lid,)
        for stmt in node.body:
            _scan_node(fn, stmt, inner, names, out)
        return
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        if name and name.endswith(".acquire"):
            lid = names.resolve(fn, node.func.value)
            if lid is not None:
                # acquisition EVENT only: without matching the
                # release we don't extend the held scope
                out.acquires.append((lid, node, held))
                name = None
        if name:
            out.calls.append((node, name, held))
    for child in ast.iter_child_nodes(node):
        _scan_node(fn, child, held, names, out)


@register_project
class LockOrderCycleRule(ProjectRule):
    id = "lock-order-cycle"
    category = "concurrency"
    severity = "error"
    description = (
        "interprocedural lock-order analysis: two locks acquired in "
        "opposite orders on different call paths (deadlock once the "
        "threads interleave), or a lock held across a known-blocking "
        "call (sleep/recv/subprocess) that stalls every other taker")

    def check(self, project: ProjectContext):
        names = _LockNames(project)
        summaries: Dict[str, _FnSummary] = {
            q: _scan_function(fi, names)
            for q, fi in project.functions.items()}

        # transitive lock set acquired by each function (memoized DFS)
        acq_memo: Dict[str, Set[str]] = {}

        def acquires(q: str, stack: Set[str]) -> Set[str]:
            if q in acq_memo:
                return acq_memo[q]
            if q in stack:
                return set()  # recursion — resolved by the outer call
            stack = stack | {q}
            s = summaries[q]
            got = {lid for lid, _, _ in s.acquires}
            for call, _name, _held in s.calls:
                target = project.resolve_call(project.functions[q],
                                              call)
                if target is not None and target.qualname in summaries:
                    got |= acquires(target.qualname, stack)
            acq_memo[q] = got
            return got

        # blocking reachability: does calling q (eventually) hit a
        # blocking call? memoized; value = dotted name or None
        blk_memo: Dict[str, Optional[str]] = {}

        def blocks(q: str, stack: Set[str]) -> Optional[str]:
            if q in blk_memo:
                return blk_memo[q]
            if q in stack:
                return None
            stack = stack | {q}
            found = None
            for call, name, _held in summaries[q].calls:
                if _is_blocking(name):
                    found = name
                    break
                target = project.resolve_call(project.functions[q],
                                              call)
                if target is not None and target.qualname in summaries:
                    deeper = blocks(target.qualname, stack)
                    if deeper is not None:
                        found = f"{name} -> {deeper}"
                        break
            blk_memo[q] = found
            return found

        # edges: L -> M means "M acquired while L held", with one
        # representative site kept per edge
        edges: Dict[str, Dict[str, Tuple[str, ast.AST, str]]] = {}

        def edge(l: str, m: str, mod: str, node: ast.AST,
                 how: str) -> None:
            if l != m:
                edges.setdefault(l, {}).setdefault(m, (mod, node, how))

        findings = []
        for q, s in summaries.items():
            fi = project.functions[q]
            ctx = project.modules[fi.module]
            for lid, node, held in s.acquires:
                for h in held:
                    edge(h, lid, fi.module, node,
                         f"'{q}' acquires {_short(lid)} while holding "
                         f"{_short(h)}")
            for call, name, held in s.calls:
                if not held:
                    continue
                if _is_blocking(name) and \
                        not self._cv_exempt(name, held, fi, names):
                    findings.append(self.at(ctx, call, (
                        f"'{q}' calls blocking '{name}' while holding "
                        f"{'/'.join(_short(h) for h in held)} — every "
                        "other taker of the lock stalls behind it; "
                        "move the blocking call outside the critical "
                        "section")))
                    continue
                target = project.resolve_call(fi, call)
                if target is None or target.qualname not in summaries:
                    continue
                for m in acquires(target.qualname, set()):
                    for h in held:
                        edge(h, m, fi.module, call,
                             f"'{q}' holds {_short(h)} and calls "
                             f"'{target.qualname}', which acquires "
                             f"{_short(m)}")
                deep = blocks(target.qualname, set())
                if deep is not None and \
                        not self._cv_exempt(deep, held, fi, names):
                    findings.append(self.at(ctx, call, (
                        f"'{q}' holds "
                        f"{'/'.join(_short(h) for h in held)} across "
                        f"'{target.qualname}', which blocks in "
                        f"{deep} — hoist the blocking work out of the "
                        "locked region")))

        findings.extend(self._cycles(project, edges))
        return findings

    @staticmethod
    def _cv_exempt(name: str, held: Tuple[str, ...], fn: FunctionInfo,
                   names: _LockNames) -> bool:
        """``cv.wait()`` style calls release the held lock — never a
        hold-across-block hazard for the lock the cv wraps."""
        last = name.rsplit(".", 1)[-1].split(" ")[0]
        return last in _CV_RELEASING

    def _cycles(self, project: ProjectContext, edges) -> List[tuple]:
        # Tarjan SCC over the acquired-while-holding graph; any SCC
        # with >1 lock (or a self-loop, which edge() already filters)
        # is an order inversion
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            for w in edges.get(v, ()):  # successors
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)

        nodes = set(edges)
        for tos in edges.values():
            nodes.update(tos)
        for v in sorted(nodes):
            if v not in index:
                strongconnect(v)

        out = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            scc_set = set(scc)
            parts = []
            anchor = None
            for l in sorted(scc_set):
                for m, (mod, node, how) in sorted(
                        edges.get(l, {}).items()):
                    if m in scc_set:
                        parts.append(how)
                        if anchor is None:
                            anchor = (mod, node)
            mod, node = anchor
            ctx = project.modules[mod]
            out.append(self.at(ctx, node, (
                "lock-order cycle among "
                + ", ".join(_short(l) for l in sorted(scc_set))
                + ": " + "; ".join(parts)
                + " — pick one global order (or collapse to one lock)")))
        return out


def _is_blocking(name: str) -> bool:
    if name in _BLOCKING_EXACT:
        return True
    last = name.rsplit(".", 1)[-1]
    return "." in name and last in _BLOCKING_ATTRS


def _short(lock_id: str) -> str:
    """``pkg.mod:Class.attr`` -> ``Class.attr`` for messages."""
    return lock_id.rsplit(":", 1)[-1]
