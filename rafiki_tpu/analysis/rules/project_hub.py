"""hub-verb-parity: the data-plane verb surface must agree everywhere.

The hub contract crosses four layers and two languages: the C++ server
dispatches on verb strings (``native/kv_server.cc``), the Python client
sends them (``KVClient._cmd("SET", ...)``), ``QueueHub`` names the
transport-neutral verb interface, and the decorators/backends
(``ChaosHub``, ``KVQueueHub``, ``InProcQueueHub``) each re-implement
that surface. PR 14 shipped a ChaosHub that silently did NOT wrap four
verbs — the base class's default no-op bodies meant nothing raised, the
injector simply never saw those calls. Exactly the bug class a
whole-program rule can make structural:

- **implementation parity** — any project class that subclasses a verb
  interface (a class with >= 3 ``raise NotImplementedError`` methods)
  and is instantiated anywhere must override every abstract method.
- **decorator parity** — a subclass that WRAPS another instance of the
  interface (``__init__`` stores a param typed/named as the interface)
  must override EVERY public method of the interface, *including the
  ones with default bodies* — a default body is precisely where a
  missed wrap hides, because nothing raises.
- **wire parity** — every verb the Python client sends
  (``*._cmd("VERB", ...)`` and ``_encode([b"VERB", ...])`` framings)
  must appear in the C++ server's dispatch (``cmd == "VERB"``).
  Server-only verbs are fine (WAL-replay internals, aliases); a client
  verb the server never dispatches is a guaranteed runtime error.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from ..astutil import dotted
from ..project import (ClassInfo, ProjectContext, ProjectRule,
                       register_project)

#: a class is treated as a verb interface once this many methods are
#: bodies of nothing but ``raise NotImplementedError``
_MIN_ABSTRACT = 3

_CC_DISPATCH_RE = re.compile(r'cmd\s*==\s*"([A-Z][A-Z0-9_]*)"')


def _is_abstract_body(fn: ast.AST) -> bool:
    body = list(fn.body)
    if body and isinstance(body[0], ast.Expr) and \
            isinstance(body[0].value, ast.Constant) and \
            isinstance(body[0].value.value, str):
        body = body[1:]  # docstring
    if len(body) != 1 or not isinstance(body[0], ast.Raise):
        return False
    exc = body[0].exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    return isinstance(exc, ast.Name) and \
        exc.id == "NotImplementedError"


def _interface_methods(info: ClassInfo) -> Dict[str, bool]:
    """public method name -> is_abstract; {} unless interface-shaped."""
    out: Dict[str, bool] = {}
    n_abstract = 0
    for name, fn in info.methods.items():
        if name.startswith("_"):
            continue
        abstract = _is_abstract_body(fn)
        out[name] = abstract
        n_abstract += abstract
    return out if n_abstract >= _MIN_ABSTRACT else {}


def _instantiated_classes(project: ProjectContext) -> Set[str]:
    out: Set[str] = set()
    for mod, ctx in project.modules.items():
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name:
                    q = project.resolve_class(mod, name)
                    if q:
                        out.add(q)
    return out


def _wrapped_param(project: ProjectContext, info: ClassInfo,
                   iface: str) -> Optional[str]:
    """If ``info.__init__`` takes and stores an instance of ``iface``
    (decorator shape), the param name; else None."""
    init = info.methods.get("__init__")
    if init is None:
        return None
    stored: Set[str] = set()
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Name):
            for t in node.targets:
                p = dotted(t)
                if p and p.startswith("self."):
                    stored.add(node.value.id)
    for arg in init.args.args + init.args.kwonlyargs:
        if arg.arg == "self" or arg.arg not in stored:
            continue
        if arg.annotation is not None:
            ann = dotted(arg.annotation)
            if ann and project.resolve_class(info.module, ann) == iface:
                return arg.arg
        if arg.arg == "inner":
            return arg.arg
    return None


@register_project
class HubVerbParityRule(ProjectRule):
    id = "hub-verb-parity"
    category = "serving"
    severity = "error"
    description = (
        "hub/data-plane verb surface drift: an interface implementation "
        "missing abstract verbs, a decorator silently passing verbs "
        "through to the wrapped hub (the ChaosHub bug), or a client "
        "verb the C++ server never dispatches")

    def check(self, project: ProjectContext):
        yield from self._class_parity(project)
        yield from self._wire_parity(project)

    # ---- interface / decorator parity ----

    def _class_parity(self, project: ProjectContext):
        interfaces = {q: m for q, m in
                      ((q, _interface_methods(i))
                       for q, i in project.classes.items()) if m}
        if not interfaces:
            return
        live = _instantiated_classes(project)
        for q, info in sorted(project.classes.items()):
            if q in interfaces:
                continue
            mro = project.class_mro(q)
            iface = next((c.qualname for c in mro[1:]
                          if c.qualname in interfaces), None)
            if iface is None:
                continue
            methods = interfaces[iface]
            # every method overridden somewhere strictly below the
            # interface in the MRO
            overridden: Set[str] = set()
            for c in mro:
                if c.qualname == iface:
                    break
                overridden |= set(c.methods)
            ctx = project.modules[info.module]
            iface_name = iface.rsplit(":", 1)[-1]
            wraps = _wrapped_param(project, info, iface)
            if wraps is not None:
                required = set(methods)
            elif q in live:
                required = {m for m, is_abs in methods.items()
                            if is_abs}
            else:
                continue  # abstract intermediate bases are fine
            missing = sorted(required - overridden)
            if not missing:
                continue
            if wraps is not None:
                msg = (
                    f"'{info.name}' wraps a {iface_name} (via "
                    f"'{wraps}') but does not override "
                    f"{', '.join(missing)} — those verbs silently "
                    "bypass the wrapper (the base default body runs "
                    "instead); wrap every verb or forward explicitly")
            else:
                msg = (
                    f"'{info.name}' is instantiated but never "
                    f"implements {iface_name}.{'/'.join(missing)} — "
                    "calls will raise NotImplementedError at runtime")
            yield self.at(ctx, info.node, msg)

    # ---- client <-> server wire parity ----

    def _wire_parity(self, project: ProjectContext):
        server = None
        for name, res in sorted(project.resources.items()):
            if not name.endswith((".cc", ".cpp")):
                continue
            verbs = set(_CC_DISPATCH_RE.findall(res.text))
            if verbs:
                server = (res, verbs)
                break
        if server is None:
            return  # no C++ side in this tree — nothing to diff
        res, served = server
        for mod, ctx in sorted(project.modules.items()):
            for node in ast.walk(ctx.tree):
                verb = _client_verb(node)
                if verb is None or verb in served:
                    continue
                yield self.at(ctx, node, (
                    f"client sends verb '{verb}' but "
                    f"{res.path.rsplit('/', 1)[-1]} has no "
                    f"'cmd == \"{verb}\"' dispatch — the server will "
                    "reject it; add the handler or drop the call"))


def _client_verb(node: ast.AST) -> Optional[str]:
    """The wire verb sent by this call node, if any.

    Two framings exist in the client: ``self._cmd("VERB", ...)`` for
    the common path, and ``self._encode([b"VERB", ...])`` for calls
    that need custom response handling (BRPOP).
    """
    if not isinstance(node, ast.Call) or not node.args:
        return None
    name = dotted(node.func) or ""
    last = name.rsplit(".", 1)[-1]
    if last == "_cmd":
        first = node.args[0]
        if isinstance(first, ast.Constant) and \
                isinstance(first.value, str) and \
                re.fullmatch(r"[A-Z][A-Z0-9_]*", first.value):
            return first.value
        return None
    if last == "_encode":
        arg: ast.AST = node.args[0]
        # _encode([b"BRPOP"] + keys + [timeout]) — take the leftmost
        # list literal in a BinOp chain
        while isinstance(arg, ast.BinOp):
            arg = arg.left
        if isinstance(arg, ast.List) and arg.elts:
            first = arg.elts[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, bytes):
                try:
                    text = first.value.decode("ascii")
                except UnicodeDecodeError:
                    return None
                if re.fullmatch(r"[A-Z][A-Z0-9_]*", text):
                    return text
    return None
