"""Path-sensitive lock rule: a manual ``.acquire()`` whose matching
``release()`` is missing on SOME path.

The per-module concurrency rules check lock *placement*; this one
checks lock *paths*. The bug class: code acquires a lock, releases it
on the straight-line path, but an early ``return`` or a raising call
between the two leaks the lock — every later waiter deadlocks. The
fix is ``with lock:`` (exempt here by construction) or try/finally.

The same acquire/release discipline governs the device-slot
allocator (``self.allocator.acquire(timeout=...)`` hands out a slot
HANDLE that must be released or handed to a live service), so the
receiver pattern covers ``alloc*`` too. Handle semantics bring escape
analysis: storing the handle (``slots.append(slot)``, ``self._slot =
slot``, ``return slot``) transfers ownership and settles the
obligation outright; passing it to a general call
(``self._spawn(..., slot=slot)``) settles it only if the call
COMPLETES — if the call raises before taking ownership, the handle
leaks with the exception, which is exactly the path this rule walks.

Arming: the plain forms arm only when the function releases the same
receiver somewhere — an acquire with NO release at all is a wrapper
method (``def lock(self): self._mu.acquire()``), a different (and
intentional) shape. The guarded timeout form (``slot = a.acquire(
timeout=...)`` + ``if slot is None:``) is self-arming: a function
that handles acquisition failure is no wrapper.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Tuple

from ..astutil import dotted
from ..dataflow import (FlowRule, functions, header_exprs, path_search,
                        register_flow)

#: receiver names that plausibly denote a lock or an acquire/release-
#: disciplined resource allocator
_LOCKISH = re.compile(r"(?:^|_)(?:lock|mutex|sem|cond|cv|alloc)\w*$",
                      re.IGNORECASE)

#: collection stores that take ownership of a handle and cannot fail
#: halfway through doing so
_STORE_METHODS = {"add", "append", "appendleft", "insert", "push",
                  "put", "put_nowait", "setdefault"}


def _lock_recv(call: ast.Call, method: str) -> Optional[str]:
    """``self._mu.acquire()`` -> ``self._mu`` when the receiver is
    lock-ish and the method matches."""
    if not isinstance(call.func, ast.Attribute) or \
            call.func.attr != method:
        return None
    recv = dotted(call.func.value)
    if recv is None:
        return None
    last = recv.rsplit(".", 1)[-1]
    return recv if _LOCKISH.search(last) else None


def _nonblocking(call: ast.Call) -> bool:
    """acquire(blocking=False) / acquire(timeout=...) may NOT hold the
    lock afterwards — only the guarded form knows."""
    for kw in call.keywords:
        if kw.arg in ("blocking", "timeout"):
            return True
    return bool(call.args)  # positional blocking/timeout


def _releases(stmt: ast.AST, recv: str) -> bool:
    for part in header_exprs(stmt):
        for node in ast.walk(part):
            if isinstance(node, ast.Call) and \
                    _lock_recv(node, "release") == recv:
                return True
    return False


def _mentions(node: ast.AST, var: str) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == var and \
                isinstance(sub.ctx, ast.Load):
            return True
    return False


def _held_guard(test: ast.AST, var: str) -> Optional[str]:
    """Which edge of ``if <test>:`` keeps the handle held.

    ``if v is None:`` / ``if not v:`` -> held on "false";
    ``if v is not None:`` / ``if v:`` -> held on "true"."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        test = test.operand
        if isinstance(test, ast.Name) and test.id == var:
            return "false"
        return None
    if isinstance(test, ast.Name) and test.id == var:
        return "true"
    if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
            isinstance(test.left, ast.Name) and test.left.id == var and \
            isinstance(test.comparators[0], ast.Constant) and \
            test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is):
            return "false"
        if isinstance(test.ops[0], ast.IsNot):
            return "true"
    return None


def _settles(stmt: ast.AST, recv: str,
             var: Optional[str]) -> Optional[str]:
    """Does this statement settle the release obligation?

    "hard" — settled even if the statement raises (release, or an
    ownership store that cannot fail halfway). "soft" — settled only
    on normal completion (handle passed to a general call that may
    raise before taking ownership). None — still held.
    """
    if _releases(stmt, recv):
        return "hard"
    if var is None:
        return None
    verdict = None
    for part in header_exprs(stmt):
        for node in ast.walk(part):
            if not (isinstance(node, ast.Call)
                    and any(_mentions(a, var) for a in
                            list(node.args)
                            + [kw.value for kw in node.keywords])):
                continue
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _STORE_METHODS:
                return "hard"
            verdict = "soft"
    if verdict:
        return verdict
    # plain store / return / alias outside any call: ownership is
    # visibly transferred and a store cannot fail halfway
    if any(_mentions(part, var) for part in header_exprs(stmt)):
        return "hard"
    return None


@register_flow
class LockReleasePathRule(FlowRule):
    id = "lock-release-path"
    category = "concurrency"
    severity = "error"
    description = (
        "a manual .acquire() misses its release() on some path "
        "(early return / raising call): that path leaks the lock or "
        "slot handle and every later waiter deadlocks or the slot is "
        "gone until restart — use `with lock:`, widen the try/finally, "
        "or release the handle before re-raising")
    sources = (
        "`lock.acquire()` as a statement (blocking acquire)",
        "`ok = lock.acquire()` without blocking=/timeout= "
        "(blocking acquire, held from the next statement)",
        "`if lock.acquire(...):` / `if not lock.acquire(...):` "
        "(held only on the succeeding branch)",
        "`slot = alloc.acquire(timeout=...)` followed by a None/"
        "falsy guard (handle held on the surviving branch)",
    )
    sinks = (
        "any function exit (return / fall-through / propagating "
        "exception) reached while the lock or handle is still held — "
        "including a raise INSIDE the call the handle was being "
        "passed to",
    )
    sanitizers = (
        "`lock.release()` on the path (usually in a finally:)",
        "`with lock:` blocks — never tracked, release is structural",
        "storing/returning the handle (ownership transfer): "
        "`slots.append(slot)`, `self._slot = slot`, `return slot`",
    )
    example = (
        "def leak(self):\n"
        "    self._lock.acquire()\n"
        "    if self.closed:\n"
        "        return          # <- exits with self._lock held\n"
        "    self.work()\n"
        "    self._lock.release()\n")

    def check(self, ctx) -> Iterator[Tuple[ast.AST, str, tuple]]:
        for fn, cfg in functions(ctx):
            released = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    recv = _lock_recv(node, "release")
                    if recv:
                        released.add(recv)
            for block, idx, stmt in cfg.statements():
                for recv, var, start in self._acquires(
                        block, idx, stmt, released):
                    hits = path_search(
                        cfg, start[0], start[1],
                        kill=lambda s, r=recv, v=var: _settles(s, r, v),
                        to_exit=True,
                        exit_note=(f"the function can exit here with "
                                   f"'{recv}' still held"),
                        soft_exc_note=(
                            f"if this call raises, the exception "
                            f"leaves the function with the handle "
                            f"from '{recv}' neither released nor "
                            f"handed over"))
                    for h in hits:
                        trace = self.trace_from_path(
                            stmt, f"'{recv}' acquired here", h)
                        yield stmt, (
                            f"'{recv}.acquire()' is not matched by a "
                            f"release() on every path — the path "
                            f"ending at line {h.stmt.lineno} leaks "
                            f"it (use `with {recv}:`, a finally that "
                            f"covers this path, or release before "
                            f"re-raising)"), trace
                        break  # one witness per acquire is enough

    def _acquires(self, block, idx, stmt, released):
        """Yield (receiver, handle var or None, held-start point)."""
        # bare statement: lock.acquire()
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Call):
            recv = _lock_recv(stmt.value, "acquire")
            if recv in released and not _nonblocking(stmt.value):
                yield recv, None, (block, idx + 1)
            return
        if isinstance(stmt, ast.Assign) and \
                isinstance(stmt.value, ast.Call) and \
                len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            recv = _lock_recv(stmt.value, "acquire")
            if recv is None:
                return
            var = stmt.targets[0].id
            if not _nonblocking(stmt.value):
                # ok = lock.acquire()  — blocking form always holds;
                # result is a bool, not a handle: no escape tracking
                if recv in released:
                    yield recv, None, (block, idx + 1)
                return
            # slot = alloc.acquire(timeout=...) + guard: self-arming
            if idx + 1 < len(block.stmts) and \
                    isinstance(block.stmts[idx + 1], ast.If):
                held_kind = _held_guard(block.stmts[idx + 1].test, var)
                if held_kind is not None:
                    for succ, kind in block.succs:
                        if kind == held_kind:
                            yield recv, var, (succ, 0)
            return
        # if lock.acquire(...):  /  if not lock.acquire(...):
        if isinstance(stmt, ast.If):
            test, held_kind = stmt.test, "true"
            if isinstance(test, ast.UnaryOp) and \
                    isinstance(test.op, ast.Not):
                test, held_kind = test.operand, "false"
            if isinstance(test, ast.Call):
                recv = _lock_recv(test, "acquire")
                if recv in released:
                    for succ, kind in block.succs:
                        if kind == held_kind:
                            yield recv, None, (succ, 0)
