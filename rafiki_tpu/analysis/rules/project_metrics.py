"""metric-catalog-drift: code, docs, and dashboard must name the same
metrics.

Three surfaces claim to describe the observability plane and nothing
used to force them to agree: the code registers instruments
(``metrics.counter("...")`` / ``StatsMap`` keys), the catalog in
``docs/observability.md`` documents them, and ``admin/dashboard.html``
reads them off the worker-stats objects (``s.engine_kv_pages_used``).
Every rename or addition that touches one surface and not the others is
silent until an operator stares at an empty dashboard panel.

The rule builds the *published-name universe* from code:

- instrument names: first-arg string constants of
  ``*.counter/gauge/histogram("name")`` and direct
  ``Counter/Gauge/Histogram("name")`` constructors (histograms also
  publish ``<name>_count``/``<name>_sum`` in snapshots);
- StatsMap keys: first-arg constants of ``*.inc/set/max_set("key")``
  — published bare, or under a prefix: ``register_stats(...,
  prefix="chaos_")`` kwargs and published f-string keys
  (``stats[f"engine_{k}"]``) contribute the prefix set;
- worker-published literal keys: ``stats["role"] = ...`` stores and
  ``stats.update({...})`` keys on a receiver named ``stats`` (the
  ``_publish_stats`` convention);
- f-string keys become shape patterns (``f"slo_{c}_ttft_p95_s"`` ->
  ``slo_*_ttft_p95_s``).

and diffs three ways: registered-but-undocumented (no mention anywhere
in the markdown catalog), documented-but-stale (a catalog TABLE row —
first-cell backticked name — matching nothing registered), and
dashboard-referenced-but-never-published (``s.<name>`` in the
dashboard matching no published key). Docs placeholders
(``slo_<class>_ttft_p95_s``) and globs (``chaos_*``) match shapes.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..astutil import dotted
from ..project import (ProjectContext, ProjectRule, TextResource,
                       register_project)

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_SHAPE_RE = re.compile(r"^[a-z*][a-z0-9_*]*$")
_BACKTICK_RE = re.compile(r"`([^`]+)`")
_DASH_REF_RE = re.compile(r"\bs\.([a-z][a-z0-9_]*)\b")

#: attribute accesses on the dashboard's stats objects that are JS,
#: not metrics
_JS_ATTRS = {"length", "map", "filter", "forEach", "join", "push",
             "sort", "slice", "toFixed", "concat", "indexOf", "trim"}

_INSTRUMENT_METHODS = {"counter", "gauge", "histogram"}
_INSTRUMENT_CTORS = {"Counter", "Gauge", "Histogram"}
_STATSMAP_WRITES = {"inc", "max_set", "set"}


def _fstring_shape(node: ast.JoinedStr) -> Optional[str]:
    parts: List[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        elif isinstance(v, ast.FormattedValue):
            parts.append("*")
        else:
            return None
    shape = "".join(parts)
    return shape if _SHAPE_RE.match(shape) and "*" in shape else None


def _shape_regex(shape: str) -> re.Pattern:
    """A catalog/code shape -> regex: ``*`` and ``<placeholder>``
    match one name segment or more."""
    pat = re.sub(r"<[^>]*>", "*", shape)
    pat = re.escape(pat).replace(r"\*", r"[a-z0-9_]+")
    return re.compile(rf"^{pat}$")


class _Universe:
    """Everything the code publishes, with match helpers."""

    def __init__(self):
        #: concrete name -> (path, line) of the defining site
        self.concrete: Dict[str, Tuple[str, int]] = {}
        #: StatsMap keys (documented bare OR under any prefix)
        self.statsmap: Dict[str, Tuple[str, int]] = {}
        self.prefixes: Set[str] = {""}
        #: shape string -> (path, line)
        self.shapes: Dict[str, Tuple[str, int]] = {}
        self._regexes: Optional[List[re.Pattern]] = None

    def all_names(self) -> Set[str]:
        names = set(self.concrete)
        for k in self.statsmap:
            names.update(p + k for p in self.prefixes)
        return names

    def published(self, name: str) -> bool:
        if name in self.concrete:
            return True
        for p in sorted(self.prefixes, key=len, reverse=True):
            if name.startswith(p) and name[len(p):] in self.statsmap:
                return True
        if self._regexes is None:
            self._regexes = [_shape_regex(s) for s in self.shapes]
        return any(r.match(name) for r in self._regexes)


def _doc_names(res: TextResource) -> Iterator[Tuple[str, int]]:
    """Backticked tokens anywhere in the markdown (the lenient,
    "is it mentioned at all" surface)."""
    for i, line in enumerate(res.lines):
        for tok in _BACKTICK_RE.findall(line):
            yield tok, i + 1


def _doc_catalog_rows(res: TextResource) -> Iterator[Tuple[str, int]]:
    """First-cell backticked names of table rows (the strict catalog
    surface the staleness check runs against)."""
    for i, line in enumerate(res.lines):
        if not line.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not cells or set(cells[0]) <= {"-", ":", " "}:
            continue  # separator row
        for tok in _BACKTICK_RE.findall(cells[0]):
            shape = re.sub(r"<[^>]*>", "*", tok)
            if _SHAPE_RE.match(shape):
                yield tok, i + 1


@register_project
class MetricCatalogDriftRule(ProjectRule):
    id = "metric-catalog-drift"
    category = "observability"
    severity = "error"
    description = (
        "metric surfaces drifted: a registered metric missing from "
        "docs/observability.md, a catalog row naming a metric the code "
        "no longer publishes, or a dashboard reference to a key no "
        "worker publishes")

    def check(self, project: ProjectContext):
        uni = self._collect(project)
        docs = project.md_resources()
        catalog = [d for d in docs
                   if d.path.endswith("observability.md")]
        yield from self._undocumented(uni, docs, catalog)
        for res in catalog:
            yield from self._stale(uni, res)
        dash = project.resource("dashboard.html")
        if dash is not None:
            yield from self._dashboard(uni, dash)

    # ---- code side ----

    def _collect(self, project: ProjectContext) -> _Universe:
        uni = _Universe()
        # pass 1: names of callables handed to register_stats —
        # their returned dict literals ARE published keys (the admin's
        # kvd_metrics() re-export pattern)
        exporters: Set[str] = set()
        for mod, ctx in sorted(project.modules.items()):
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call) and \
                        (dotted(node.func) or "").rsplit(".", 1)[-1] \
                        == "register_stats" and node.args:
                    arg = dotted(node.args[0])
                    if arg:
                        exporters.add(arg.rsplit(".", 1)[-1])
        for mod, ctx in sorted(project.modules.items()):
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call):
                    self._collect_call(ctx.path, node, uni)
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    self._collect_store(ctx.path, node, uni)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)) and \
                        node.name in exporters:
                    # everything an exporter builds is published: dict
                    # literals AND incremental out[f"kvd_{k}"] = ...
                    # subscript stores (kvd_metrics' loop idiom)
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Dict):
                            self._collect_keys(ctx.path, sub, uni)
                        elif isinstance(sub, ast.Assign):
                            for t in sub.targets:
                                if isinstance(t, ast.Subscript):
                                    self._collect_key_node(
                                        ctx.path, t.slice, uni,
                                        statsmap=True)
        return uni

    def _collect_call(self, path: str, node: ast.Call,
                      uni: _Universe) -> None:
        name = dotted(node.func)
        if not name:
            return
        last = name.rsplit(".", 1)[-1]
        loc = (path, node.lineno)
        first = node.args[0] if node.args else None
        first_str = first.value if (
            isinstance(first, ast.Constant)
            and isinstance(first.value, str)) else None
        if (("." in name and last in _INSTRUMENT_METHODS)
                or name in _INSTRUMENT_CTORS) and first_str and \
                _NAME_RE.match(first_str):
            uni.concrete.setdefault(first_str, loc)
            if last == "histogram" or name == "Histogram":
                # snapshot exporters flatten histograms
                uni.concrete.setdefault(first_str + "_count", loc)
                uni.concrete.setdefault(first_str + "_sum", loc)
        elif "." in name and last in _STATSMAP_WRITES:
            # .set() is generic; demand a receiver path so a bare
            # set(...) builtin call never lands here, and skip
            # known non-metric receivers (threading.Event has no
            # string-arg set, so in practice this is StatsMap)
            if first_str and _NAME_RE.match(first_str):
                uni.statsmap.setdefault(first_str, loc)
            elif isinstance(first, ast.JoinedStr):
                shape = _fstring_shape(first)
                if shape:  # .inc(f"requests_shed_{cls}")
                    uni.shapes.setdefault(shape, loc)
        elif last == "StatsMap" or name == "StatsMap":
            # StatsMap({"requests_shed_batch": 0, ...}) seeds keys
            if isinstance(first, ast.Dict):
                for k in first.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str) and \
                            _NAME_RE.match(k.value):
                        uni.statsmap.setdefault(
                            k.value, (path, k.lineno))
        elif last == "register_stats":
            for kw in node.keywords:
                if kw.arg == "prefix" and \
                        isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, str):
                    uni.prefixes.add(kw.value.value)
        elif last == "update" and "." in name and \
                name.rsplit(".", 2)[-2] == "stats" and node.args:
            self._collect_keys(path, node.args[0], uni)

    def _collect_store(self, path: str, node: ast.AST,
                       uni: _Universe) -> None:
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            recv = dotted(t)
            if recv is not None and \
                    recv.rsplit(".", 1)[-1] == "stats" and \
                    isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Dict):
                # self.stats = {"decode_steps": 0, ...} — the decode
                # engine's plain-dict stats surface
                for k in node.value.keys:
                    if k is not None:
                        self._collect_key_node(path, k, uni,
                                               statsmap=True)
            if not (isinstance(t, ast.Subscript) and
                    (dotted(t.value) or "").rsplit(".", 1)[-1]
                    == "stats"):
                continue
            self._collect_key_node(path, t.slice, uni,
                                   statsmap=True)

    def _collect_keys(self, path: str, node: ast.AST,
                      uni: _Universe) -> None:
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if k is not None:
                    self._collect_key_node(path, k, uni,
                                           statsmap=True)
        elif isinstance(node, ast.DictComp):
            self._collect_key_node(path, node.key, uni,
                                   statsmap=True)

    def _collect_key_node(self, path: str, node: ast.AST,
                          uni: _Universe,
                          statsmap: bool = False) -> None:
        loc = (path, getattr(node, "lineno", 1))
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                _NAME_RE.match(node.value):
            bucket = uni.statsmap if statsmap else uni.concrete
            bucket.setdefault(node.value, loc)
        elif isinstance(node, ast.JoinedStr):
            shape = _fstring_shape(node)
            if shape:
                uni.shapes.setdefault(shape, loc)
                if shape.endswith("*") and shape.count("*") == 1:
                    # f"engine_{k}" republishes a StatsMap under a
                    # prefix — let docs document the prefixed form
                    uni.prefixes.add(shape[:-1])

    # ---- diffs ----

    def _undocumented(self, uni: _Universe, docs, catalog):
        if not catalog:
            return  # no catalog in this tree — nothing to hold code to
        mentioned: Set[str] = set()
        patterns: List[re.Pattern] = []
        for res in docs:
            for tok, _line in _doc_names(res):
                shape = re.sub(r"<[^>]*>", "*", tok)
                if "*" in shape and _SHAPE_RE.match(shape):
                    patterns.append(_shape_regex(tok))
                else:
                    mentioned.add(tok)

        def documented(name: str) -> bool:
            return name in mentioned or \
                any(p.match(name) for p in patterns)

        for name, (path, line) in sorted(uni.concrete.items()):
            if name.endswith(("_count", "_sum")) and \
                    name.rsplit("_", 1)[0] in uni.concrete:
                continue  # histogram expansions ride the base name
            if not documented(name):
                yield (path, line, 0,
                       f"metric '{name}' is registered here but "
                       "appears nowhere in docs/observability.md — "
                       "add a catalog row (or rename to a documented "
                       "name)")
        for key, (path, line) in sorted(uni.statsmap.items()):
            if not any(documented(p + key)
                       for p in sorted(uni.prefixes)):
                yield (path, line, 0,
                       f"stats key '{key}' is published here (bare or "
                       "via a registered prefix) but no form of it is "
                       "documented in docs/observability.md")

    def _stale(self, uni: _Universe, res: TextResource):
        for tok, line in _doc_catalog_rows(res):
            shape = re.sub(r"<[^>]*>", "*", tok)
            if "*" in shape:
                rx = _shape_regex(tok)
                if any(rx.match(n) for n in uni.all_names()) or \
                        shape in uni.shapes:
                    continue
            elif uni.published(tok):
                continue
            yield (res.path, line, 0,
                   f"catalog row documents '{tok}' but the code no "
                   "longer publishes it — drop the row or restore the "
                   "metric")

    def _dashboard(self, uni: _Universe, res: TextResource):
        seen: Set[str] = set()
        for i, text in enumerate(res.lines):
            for name in _DASH_REF_RE.findall(text):
                if name in _JS_ATTRS or name in seen:
                    continue
                seen.add(name)
                if not uni.published(name):
                    yield (res.path, i + 1, 0,
                           f"dashboard reads 's.{name}' but no worker "
                           "publishes that key — the panel renders "
                           "undefined; fix the reference or publish "
                           "the key")
