"""Python client + lifecycle helpers for the native kv/queue server.

The server (``kv_server.cc``) is the rebuild's Redis: the reference keeps
trial parameter blobs and the predictor's query/prediction queues in a
Redis container (SURVEY.md §2, §5.8(b)); here the same data plane is a
single small C++ binary on the TPU-VM host. The wire protocol is a
RESP-compatible subset, so this client is a thin framing layer.

Crash survival (two halves, both here):

- **Server side**: :class:`KVServer` can spawn the kvd with a
  ``--data-dir`` so every mutating command lands in a CRC-checksummed
  WAL (compacted into an atomic-rename snapshot); a respawned kvd
  replays it and picks up where the dead one stopped.
- **Client side**: :class:`KVClient` owns a reconnect-with-exponential-
  backoff layer. Verbs with idempotent replay semantics (reads, SET,
  DEL, EXPIRE, and the dedup-id pushes) are retried transparently
  across a connection drop for up to ``retry_window_s``; a blocked
  ``BRPOP`` resumes on the new socket with its remaining timeout.
  Non-idempotent verbs (plain LPUSH/RPUSH, INCR) are NOT retried — a
  reconnecting caller must use the dedup pushes
  (:meth:`KVClient.lpush_dedup`) so a retry can never double-deliver.
  Reconnects/retries count into the module-level :data:`CLIENT_STATS`
  (``hub_reconnects_total`` / ``hub_rpc_retries_total``), which
  workers and the predictor re-export on their ``/metrics``.
"""

from __future__ import annotations

import logging
import os
import shutil
import socket
import subprocess
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ..obs.metrics import StatsMap

_NATIVE_DIR = Path(__file__).resolve().parent
_BINARY = _NATIVE_DIR / "build" / "rafiki-kvd"

#: process-wide client-resilience counters, re-exported on every
#: /metrics surface that talks to the kvd (one socket layer, one truth)
CLIENT_STATS = StatsMap({"hub_reconnects_total": 0,
                         "hub_rpc_retries_total": 0})

#: verbs whose replay is idempotent (reads; SET/DEL/EXPIRE which
#: overwrite; dedup pushes which the server's recent-set makes safe;
#: STATS). Plain pops are included: a retried pop is a fresh command —
#: see the at-most-once note on :meth:`KVClient._cmd`.
_RETRYABLE = frozenset({
    "PING", "GET", "SET", "DEL", "EXISTS", "KEYS", "EXPIRE", "TTL",
    "LLEN", "LPUSHD", "RPUSHD", "LPOP", "RPOP", "STATS", "FLUSHALL"})


#: buildable native artifacts and their sources (Makefile targets)
_SOURCES = {"rafiki-kvd": "kv_server.cc", "librbpe.so": "bpe_encoder.cc"}

#: sanitizer modes the Makefile knows (SANITIZE=...); instrumented
#: artifacts get distinct names so they never shadow production ones
_SANITIZERS = ("address", "thread", "undefined")


def _artifact_name(target: str, sanitize: Optional[str]) -> str:
    """``rafiki-kvd``+address -> ``rafiki-kvd-address``;
    ``librbpe.so``+address -> ``librbpe-address.so``."""
    if not sanitize:
        return target
    stem, dot, ext = target.partition(".")
    return f"{stem}-{sanitize}{dot}{ext}"


def ensure_built(force: bool = False,
                 target: str = "rafiki-kvd",
                 sanitize: Optional[str] = None) -> Path:
    """Compile a native artifact if missing/stale; returns its path.

    Builds ONLY the named Makefile target (a broken sibling source
    must not disable this one), and the Makefile installs via
    temp-file + atomic rename so processes holding the old artifact
    keep a valid inode. ``sanitize`` selects an instrumented flavor
    (``address``/``thread``/``undefined``) built under its own name."""
    if sanitize is not None and sanitize not in _SANITIZERS:
        raise ValueError(f"bad sanitize mode {sanitize!r} "
                         f"({'|'.join(_SANITIZERS)})")
    out = _NATIVE_DIR / "build" / _artifact_name(target, sanitize)
    src = _NATIVE_DIR / _SOURCES[target]
    if not force and out.exists() and \
            out.stat().st_mtime >= src.stat().st_mtime:
        return out
    make = shutil.which("make")
    if make is None:
        raise RuntimeError(f"`make` not found; cannot build {target}")
    cmd = [make, "-C", str(_NATIVE_DIR), str(out)]
    if sanitize:
        cmd.append(f"SANITIZE={sanitize}")
    subprocess.run(cmd, check=True, capture_output=True)
    return out


class KVServer:
    """Spawn/own a rafiki-kvd process (test + single-host deployments).

    ``data_dir`` arms WAL + snapshot persistence: the server replays it
    at boot, so a respawn on the same dir (and, for live clients, the
    same port) restores every durable blob, queue, and dedup id. A
    boot that refuses a corrupt WAL (server exit code 4) surfaces here
    as a RuntimeError carrying the server's structured JSON error."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 data_dir: Optional[str] = None,
                 fsync: Optional[str] = None,
                 wal_rotate_bytes: Optional[int] = None,
                 sanitize: Optional[str] = None) -> None:
        # RAFIKI_KVD_SANITIZE lets a whole test run opt into an
        # instrumented kvd without touching call sites
        if sanitize is None:
            sanitize = os.environ.get("RAFIKI_KVD_SANITIZE") or None
        binary = ensure_built(sanitize=sanitize)
        cmd = [str(binary), "--host", host, "--port", str(port)]
        if data_dir:
            cmd += ["--data-dir", str(data_dir)]
        if fsync:
            if fsync not in ("always", "everysec", "no"):
                raise ValueError(f"bad fsync policy {fsync!r} "
                                 "(always|everysec|no)")
            cmd += ["--fsync", fsync]
        if wal_rotate_bytes:
            cmd += ["--wal-rotate-bytes", str(int(wal_rotate_bytes))]
        self.data_dir = str(data_dir) if data_dir else None
        self._proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      text=True)
        line = self._proc.stdout.readline()  # "... listening on H:P"
        if "listening on" not in line:
            # a corrupt WAL prints a structured JSON error and exits 4
            # instead of serving wrong state — surface that verbatim
            self._proc.wait(timeout=5)
            raise RuntimeError(f"rafiki-kvd failed to start: {line!r} "
                               f"(rc={self._proc.returncode})")
        hp = line.rsplit(" ", 1)[-1].strip()
        self.host, _, port_s = hp.partition(":")
        self.port = int(port_s)

    def stop(self) -> None:
        try:
            KVClient(self.host, self.port).shutdown()
        except OSError:
            pass
        try:
            self._proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self._proc.kill()

    def __enter__(self) -> "KVServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def _encode(args: List[bytes]) -> bytes:
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        out.append(b"$%d\r\n%s\r\n" % (len(a), a))
    return b"".join(out)


class KVClient:
    """Blocking client; thread-safe (one socket, one lock).

    For concurrent blocking pops (inference workers) use one client per
    thread — a BRPOP holds the socket for up to its timeout.

    ``retry_window_s > 0`` arms the reconnect layer: a connection error
    on a retryable verb triggers reconnect-with-exponential-backoff and
    a transparent re-send for up to that many seconds before a
    ``ConnectionError`` finally surfaces. The window bounds how long a
    caller can stall on a dead data plane — the predictor keeps it
    short (fast-fail into a structured 503), workers keep it long
    enough to ride out a supervised kvd respawn + WAL replay.

    At-most-once edge: a non-blocking pop whose reply is lost between
    the server's WAL append and the socket write loses that one message
    on retry. The window is microseconds around a server crash; queue
    consumers that cannot tolerate it already re-request via their own
    end-to-end protocol (stream resumes, gather timeouts).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 6399,
                 connect_timeout: float = 5.0,
                 retry_window_s: float = 0.0,
                 op_timeout_s: Optional[float] = None) -> None:
        """``op_timeout_s`` bounds every socket read/write (None = the
        default, block forever — what BRPOP holders need). Probe-style
        callers (the admin's cached STATS scrape) set it so a wedged
        or compaction-busy kvd surfaces as a caught timeout instead of
        hanging the prober."""
        self._host, self._port = host, port
        self._connect_timeout = connect_timeout
        self._op_timeout_s = op_timeout_s
        self.retry_window_s = float(retry_window_s)
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._connect()  # constructor contract: raises if unreachable

    # ---- connection lifecycle ----
    def _connect(self) -> None:
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._connect_timeout)
        sock.settimeout(self._op_timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._buf = b""

    def _teardown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError as e:
                logging.getLogger(__name__).debug(
                    "kv socket close failed: %s", e)
            self._sock = None
        self._buf = b""

    def drop_conn(self) -> None:
        """Force-close the socket (chaos / tests): the next command
        finds a dead transport and exercises the reconnect layer."""
        with self._lock:
            self._teardown()

    # ---- framing ----
    def _recv_more(self) -> None:
        if self._sock is None:
            raise ConnectionError("kv client not connected")
        chunk = self._sock.recv(65536)
        if not chunk:
            raise ConnectionError("kv server closed connection")
        self._buf += chunk

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            self._recv_more()
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_n(self, n: int) -> bytes:
        while len(self._buf) < n:
            self._recv_more()
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_reply(self):
        line = self._read_line()
        tag, rest = line[:1], line[1:]
        if tag == b"+":
            return rest.decode()
        if tag == b"-":
            raise RuntimeError(rest.decode())
        if tag == b":":
            return int(rest)
        if tag == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = self._read_n(n)
            self._read_n(2)  # CRLF
            return data
        if tag == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RuntimeError(f"bad reply tag {line!r}")

    def _send_recv(self, enc: bytes):
        if self._sock is None:
            raise ConnectionError("kv client not connected")
        self._sock.sendall(enc)
        return self._read_reply()

    def _reconnect_and_retry(self, enc: bytes, verb: str,
                             first_err: Exception,
                             deadline: Optional[float] = None):
        """The reconnect layer: exponential backoff up to
        ``retry_window_s`` (or an explicit monotonic ``deadline``),
        re-sending ``enc`` after each successful reconnect. Caller
        holds the lock. Raises ConnectionError when the window
        closes."""
        log = logging.getLogger(__name__)
        if deadline is None:
            deadline = time.monotonic() + self.retry_window_s
        backoff = 0.05
        last: Exception = first_err
        log.warning("kv connection lost during %s (%s): retrying for "
                    "up to %.1fs", verb, first_err,
                    max(0.0, deadline - time.monotonic()))
        while True:
            self._teardown()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ConnectionError(
                    f"kv server {self._host}:{self._port} unreachable "
                    f"after retry window ({verb}): {last}") from last
            time.sleep(min(backoff, remaining))
            backoff = min(backoff * 2, 1.0)
            try:
                self._connect()
                CLIENT_STATS.inc("hub_reconnects_total")
                CLIENT_STATS.inc("hub_rpc_retries_total")
                return self._send_recv(enc)
            except (OSError, ConnectionError) as e:
                last = e  # next loop iteration backs off and re-tries

    def _cmd(self, *args) -> object:
        enc_args = [a if isinstance(a, bytes) else str(a).encode()
                    for a in args]
        verb = enc_args[0].decode().upper()
        enc = _encode(enc_args)
        with self._lock:
            try:
                if self._sock is None:
                    # a prior drop/teardown left no transport: treat
                    # like a mid-command drop (retry path decides)
                    raise ConnectionError("kv client not connected")
                # _lock is held across the socket round-trip by
                # design: one socket carries one request/response at
                # a time, so the recv IS the critical section (see
                # docs/linting.md "KV client serialization")
                return self._send_recv(enc)  # rafiki: noqa[lock-order-cycle]
            except (OSError, ConnectionError) as e:
                if self.retry_window_s <= 0 or verb not in _RETRYABLE:
                    self._teardown()
                    raise ConnectionError(
                        f"kv server {self._host}:{self._port} "
                        f"connection lost ({verb}): {e}") from e
                # reconnect backoff must also stay under _lock: other
                # threads' commands cannot use the dead socket anyway
                return self._reconnect_and_retry(enc, verb, e)  # rafiki: noqa[lock-order-cycle]

    # ---- api ----
    def ping(self) -> bool:
        return self._cmd("PING") == "PONG"

    def set(self, key: str, value: bytes) -> None:
        self._cmd("SET", key, value)

    def get(self, key: str) -> Optional[bytes]:
        return self._cmd("GET", key)

    def delete(self, *keys: str) -> int:
        return int(self._cmd("DEL", *keys))

    def exists(self, key: str) -> bool:
        return bool(self._cmd("EXISTS", key))

    def keys(self, pattern: str = "*") -> List[str]:
        return sorted(k.decode() for k in self._cmd("KEYS", pattern))

    def incr(self, key: str) -> int:
        return int(self._cmd("INCR", key))

    def lpush(self, key: str, *values: bytes) -> int:
        return int(self._cmd("LPUSH", key, *values))

    def rpush(self, key: str, *values: bytes) -> int:
        return int(self._cmd("RPUSH", key, *values))

    def lpush_dedup(self, key: str, dedup_id: str, *values: bytes) -> int:
        """Deduplicated LPUSH: the server keeps a bounded recent-set of
        ``dedup_id``s (persisted in the WAL), so a RETRY of this exact
        push — after a connection drop or a kvd respawn — never
        double-delivers. The id is client-minted (uuid per logical
        push)."""
        return int(self._cmd("LPUSHD", key, dedup_id, *values))

    def rpush_dedup(self, key: str, dedup_id: str, *values: bytes) -> int:
        return int(self._cmd("RPUSHD", key, dedup_id, *values))

    def lpop(self, key: str) -> Optional[bytes]:
        return self._cmd("LPOP", key)

    def rpop(self, key: str) -> Optional[bytes]:
        return self._cmd("RPOP", key)

    def llen(self, key: str) -> int:
        return int(self._cmd("LLEN", key))

    def expire(self, key: str, seconds: float) -> None:
        """Condemn ``key`` (kv or list) ``seconds`` from now. kvd delta
        vs Redis: the key need not exist yet and the TTL survives
        DEL/recreation until it fires — see kv_server.cc."""
        self._cmd("EXPIRE", key, seconds)

    def ttl(self, key: str) -> int:
        """Redis semantics: -2 missing key, -1 no expiry, else whole
        seconds remaining."""
        return int(self._cmd("TTL", key))

    def brpop(self, keys, timeout: float
              ) -> Optional[Tuple[str, bytes]]:
        """Blocking tail-pop across ``keys``; None on timeout.

        With the reconnect layer armed, a connection lost mid-wait
        RESUMES on a fresh socket with the remaining timeout — an
        in-flight blocking pop survives a kvd respawn (the queue
        content survives via the WAL)."""
        if isinstance(keys, str):
            keys = [keys]
        enc_keys = list(keys)
        deadline = None if timeout <= 0 else time.monotonic() + timeout
        while True:
            remaining = timeout if deadline is None \
                else deadline - time.monotonic()
            if deadline is not None and remaining <= 0:
                return None
            enc = _encode([b"BRPOP"]
                          + [k.encode() if isinstance(k, str) else k
                             for k in enc_keys]
                          + [str(remaining).encode()])
            with self._lock:
                try:
                    if self._sock is None:
                        raise ConnectionError("kv client not connected")
                    # held across the blocking pop on purpose: the
                    # socket is single-flight (see docs/linting.md
                    # "KV client serialization")
                    reply = self._send_recv(enc)  # rafiki: noqa[lock-order-cycle]
                except (OSError, ConnectionError) as e:
                    if self.retry_window_s <= 0:
                        self._teardown()
                        raise ConnectionError(
                            f"kv server {self._host}:{self._port} "
                            f"connection lost (BRPOP): {e}") from e
                    # reconnect within the retry window, then LOOP to
                    # reissue with the remaining pop budget (the wait
                    # budget itself is the caller's, not the window's)
                    retry_dl = time.monotonic() + self.retry_window_s
                    if deadline is not None:
                        retry_dl = max(retry_dl, deadline)
                    reply = self._reconnect_and_retry(  # rafiki: noqa[lock-order-cycle]
                        _encode([b"PING"]), "BRPOP", e,
                        deadline=retry_dl)
                    if reply != "PONG":
                        raise ConnectionError(
                            "kv server answered garbage to the "
                            "reconnect probe") from e
                    continue  # fresh socket: reissue the blocking pop
            if reply is None:
                return None
            k, v = reply
            return k.decode(), v

    def stats(self) -> Dict[str, object]:
        """The kvd's ``STATS`` verb (persistence health): wal_bytes,
        snapshot_bytes, snapshot_age_s, last_fsync_age_s,
        replay_seconds, replayed_records, wal_truncated_bytes,
        compactions, dedup_ids, keys, lists, fsync_policy."""
        raw = self._cmd("STATS")
        out: Dict[str, object] = {}
        for line in (raw or b"").decode().splitlines():
            key, _, val = line.partition(" ")
            if not key:
                continue
            try:
                out[key] = int(val)
            except ValueError:
                try:
                    out[key] = float(val)
                except ValueError:
                    out[key] = val
        return out

    def compact(self) -> None:
        """Force a WAL compaction into a fresh snapshot (operator /
        test hook; the server also rotates automatically past
        ``--wal-rotate-bytes``)."""
        self._cmd("COMPACT")

    def flushall(self) -> None:
        self._cmd("FLUSHALL")

    def shutdown(self) -> None:
        try:
            self._cmd("SHUTDOWN")
        except (ConnectionError, RuntimeError):
            pass

    def close(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass


def wait_for_server(host: str, port: int, timeout: float = 10.0) -> KVClient:
    """Connect with retries until the server answers PING."""
    deadline = time.monotonic() + timeout
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            c = KVClient(host, port, connect_timeout=1.0)
            if c.ping():
                return c
        except OSError as e:
            last = e
            time.sleep(0.05)
    raise TimeoutError(f"kv server at {host}:{port} not up: {last}")
