"""Python client + lifecycle helpers for the native kv/queue server.

The server (``kv_server.cc``) is the rebuild's Redis: the reference keeps
trial parameter blobs and the predictor's query/prediction queues in a
Redis container (SURVEY.md §2, §5.8(b)); here the same data plane is a
single small C++ binary on the TPU-VM host. The wire protocol is a
RESP-compatible subset, so this client is a thin framing layer.
"""

from __future__ import annotations

import os
import shutil
import socket
import subprocess
import threading
import time
from pathlib import Path
from typing import List, Optional, Tuple

_NATIVE_DIR = Path(__file__).resolve().parent
_BINARY = _NATIVE_DIR / "build" / "rafiki-kvd"


#: buildable native artifacts and their sources (Makefile targets)
_SOURCES = {"rafiki-kvd": "kv_server.cc", "librbpe.so": "bpe_encoder.cc"}


def ensure_built(force: bool = False,
                 target: str = "rafiki-kvd") -> Path:
    """Compile a native artifact if missing/stale; returns its path.

    Builds ONLY the named Makefile target (a broken sibling source
    must not disable this one), and the Makefile installs via
    temp-file + atomic rename so processes holding the old artifact
    keep a valid inode."""
    out = _NATIVE_DIR / "build" / target
    src = _NATIVE_DIR / _SOURCES[target]
    if not force and out.exists() and \
            out.stat().st_mtime >= src.stat().st_mtime:
        return out
    make = shutil.which("make")
    if make is None:
        raise RuntimeError(f"`make` not found; cannot build {target}")
    subprocess.run([make, "-C", str(_NATIVE_DIR), str(out)], check=True,
                   capture_output=True)
    return out


class KVServer:
    """Spawn/own a rafiki-kvd process (test + single-host deployments)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        binary = ensure_built()
        self._proc = subprocess.Popen(
            [str(binary), "--host", host, "--port", str(port)],
            stdout=subprocess.PIPE, text=True)
        line = self._proc.stdout.readline()  # "... listening on H:P"
        if "listening on" not in line:
            raise RuntimeError(f"rafiki-kvd failed to start: {line!r}")
        hp = line.rsplit(" ", 1)[-1].strip()
        self.host, _, port_s = hp.partition(":")
        self.port = int(port_s)

    def stop(self) -> None:
        try:
            KVClient(self.host, self.port).shutdown()
        except OSError:
            pass
        try:
            self._proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self._proc.kill()

    def __enter__(self) -> "KVServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def _encode(args: List[bytes]) -> bytes:
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        out.append(b"$%d\r\n%s\r\n" % (len(a), a))
    return b"".join(out)


class KVClient:
    """Blocking client; thread-safe (one socket, one lock).

    For concurrent blocking pops (inference workers) use one client per
    thread — a BRPOP holds the socket for up to its timeout.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 6399,
                 connect_timeout: float = 5.0) -> None:
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b""
        self._lock = threading.Lock()

    # ---- framing ----
    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("kv server closed connection")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_n(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("kv server closed connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_reply(self):
        line = self._read_line()
        tag, rest = line[:1], line[1:]
        if tag == b"+":
            return rest.decode()
        if tag == b"-":
            raise RuntimeError(rest.decode())
        if tag == b":":
            return int(rest)
        if tag == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = self._read_n(n)
            self._read_n(2)  # CRLF
            return data
        if tag == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RuntimeError(f"bad reply tag {line!r}")

    def _cmd(self, *args) -> object:
        enc = [a if isinstance(a, bytes) else str(a).encode()
               for a in args]
        with self._lock:
            self._sock.sendall(_encode(enc))
            return self._read_reply()

    # ---- api ----
    def ping(self) -> bool:
        return self._cmd("PING") == "PONG"

    def set(self, key: str, value: bytes) -> None:
        self._cmd("SET", key, value)

    def get(self, key: str) -> Optional[bytes]:
        return self._cmd("GET", key)

    def delete(self, *keys: str) -> int:
        return int(self._cmd("DEL", *keys))

    def exists(self, key: str) -> bool:
        return bool(self._cmd("EXISTS", key))

    def keys(self, pattern: str = "*") -> List[str]:
        return sorted(k.decode() for k in self._cmd("KEYS", pattern))

    def incr(self, key: str) -> int:
        return int(self._cmd("INCR", key))

    def lpush(self, key: str, *values: bytes) -> int:
        return int(self._cmd("LPUSH", key, *values))

    def rpush(self, key: str, *values: bytes) -> int:
        return int(self._cmd("RPUSH", key, *values))

    def lpop(self, key: str) -> Optional[bytes]:
        return self._cmd("LPOP", key)

    def rpop(self, key: str) -> Optional[bytes]:
        return self._cmd("RPOP", key)

    def llen(self, key: str) -> int:
        return int(self._cmd("LLEN", key))

    def expire(self, key: str, seconds: float) -> None:
        """Condemn ``key`` (kv or list) ``seconds`` from now. kvd delta
        vs Redis: the key need not exist yet and the TTL survives
        DEL/recreation until it fires — see kv_server.cc."""
        self._cmd("EXPIRE", key, seconds)

    def ttl(self, key: str) -> int:
        """Redis semantics: -2 missing key, -1 no expiry, else whole
        seconds remaining."""
        return int(self._cmd("TTL", key))

    def brpop(self, keys, timeout: float
              ) -> Optional[Tuple[str, bytes]]:
        """Blocking tail-pop across ``keys``; None on timeout."""
        if isinstance(keys, str):
            keys = [keys]
        reply = self._cmd("BRPOP", *keys, timeout)
        if reply is None:
            return None
        k, v = reply
        return k.decode(), v

    def flushall(self) -> None:
        self._cmd("FLUSHALL")

    def shutdown(self) -> None:
        try:
            self._cmd("SHUTDOWN")
        except (ConnectionError, RuntimeError):
            pass

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def wait_for_server(host: str, port: int, timeout: float = 10.0) -> KVClient:
    """Connect with retries until the server answers PING."""
    deadline = time.monotonic() + timeout
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            c = KVClient(host, port, connect_timeout=1.0)
            if c.ping():
                return c
        except OSError as e:
            last = e
            time.sleep(0.05)
    raise TimeoutError(f"kv server at {host}:{port} not up: {last}")
