"""Read-only WAL/snapshot inspection for the kvd persistence files.

The doctor's data-plane check (and the persistence tests) need to judge
a kvd's durable state WITHOUT booting a server against it — a dry-run
replay that validates framing and CRCs, counts what a real boot would
restore, and reports torn tails and corruption the same way
``kv_server.cc``'s loader does. Pure reads: this module never truncates,
never repairs, never writes — safe against a LIVE data dir (the scan
races an appending server only into a benign torn-tail verdict).

Record framing (mirrors kv_server.cc): ``[u32 len][u32 crc32(payload)]
[payload]`` with payload ``[u32 nargs]([u32 len][bytes])*``, all
little-endian host order.
"""

from __future__ import annotations

import struct
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

#: mutating verbs a WAL record may carry (anything else = corruption or
#: a WAL from a newer server — either way, a real boot would refuse it).
#: EPOCH/WALHDR are the snapshot↔WAL pairing markers (see
#: kv_server.cc CompactLocked): state no-ops with gating meaning.
_KNOWN_VERBS = frozenset({
    "SET", "DEL", "LPUSH", "RPUSH", "LPUSHD", "RPUSHD", "LPOP", "RPOP",
    "EXPIRE", "DEDUP", "FLUSHALL", "EPOCH", "WALHDR"})


def _pairing_epochs(snap_records, wal_records) -> Tuple[int, int]:
    """(snapshot epoch, wal header epoch); 0 = absent. Mirrors the
    boot loader's gate: a snapshot-bearing data dir only replays a WAL
    whose first record is a matching WALHDR."""
    snap_epoch = wal_epoch = 0
    if snap_records and snap_records[0][0].upper() == b"EPOCH":
        snap_epoch = int(snap_records[0][1])
    if wal_records and wal_records[0][0].upper() == b"WALHDR":
        wal_epoch = int(wal_records[0][1])
    return snap_epoch, wal_epoch


def scan_file(path: Path) -> Dict[str, Any]:
    """Scan one persistence file. Returns::

        {"path", "exists", "bytes", "records", "torn_tail_bytes",
         "corrupt_at": Optional[int], "corrupt_detail": Optional[str]}

    ``corrupt_at`` is the offset of the first CRC-corrupt/undecodable
    record (a real boot fails there with a structured error);
    ``torn_tail_bytes`` counts an incomplete record at EOF (a real boot
    truncates it loudly and serves)."""
    out: Dict[str, Any] = {
        "path": str(path), "exists": path.exists(), "bytes": 0,
        "records": 0, "torn_tail_bytes": 0, "corrupt_at": None,
        "corrupt_detail": None}
    if not out["exists"]:
        return out
    buf = path.read_bytes()
    out["bytes"] = len(buf)
    off = 0
    while off < len(buf):
        if off + 8 > len(buf):
            break  # torn header
        length, crc = struct.unpack_from("<II", buf, off)
        if length > (1 << 30):
            out["corrupt_at"] = off
            out["corrupt_detail"] = \
                f"record length {length} exceeds 1GiB bound"
            return out
        if off + 8 + length > len(buf):
            break  # torn payload
        payload = buf[off + 8:off + 8 + length]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            out["corrupt_at"] = off
            out["corrupt_detail"] = "crc mismatch"
            return out
        args = _decode_args(payload)
        if args is None or not args or \
                args[0].decode("latin-1").upper() not in _KNOWN_VERBS:
            out["corrupt_at"] = off
            out["corrupt_detail"] = "undecodable record"
            return out
        out["records"] += 1
        off += 8 + length
    out["torn_tail_bytes"] = len(buf) - off
    return out


def _decode_args(payload: bytes) -> Optional[List[bytes]]:
    if len(payload) < 4:
        return None
    (nargs,) = struct.unpack_from("<I", payload, 0)
    args: List[bytes] = []
    p = 4
    for _ in range(nargs):
        if p + 4 > len(payload):
            return None
        (alen,) = struct.unpack_from("<I", payload, p)
        p += 4
        if p + alen > len(payload):
            return None
        args.append(payload[p:p + alen])
        p += alen
    return args


def iter_records(path: Path) -> List[List[bytes]]:
    """Decoded records of one clean file (raises ValueError at the
    first corrupt record — callers wanting a verdict use
    :func:`scan_file`)."""
    rep = scan_file(path)
    if rep["corrupt_at"] is not None:
        raise ValueError(
            f"{path}: corrupt record at offset {rep['corrupt_at']} "
            f"({rep['corrupt_detail']})")
    out: List[List[bytes]] = []
    if not path.exists():
        return out
    buf = path.read_bytes()
    off = 0
    while off + 8 <= len(buf):
        length, _ = struct.unpack_from("<II", buf, off)
        if off + 8 + length > len(buf):
            break
        args = _decode_args(buf[off + 8:off + 8 + length])
        if args:
            out.append(args)
        off += 8 + length
    return out


def dry_run_replay(data_dir: str) -> Dict[str, Any]:
    """The doctor's data-plane integrity verdict: scan snapshot + WAL
    like a boot would, WITHOUT writing anything, and summarize what a
    replay restores. ``ok`` is False when a real boot would REFUSE
    (corrupt records); a torn WAL tail is reported but not fatal —
    boots truncate it loudly and serve."""
    dd = Path(data_dir)
    snap = scan_file(dd / "snapshot.wal")
    wal = scan_file(dd / "wal")
    report: Dict[str, Any] = {
        "data_dir": str(dd), "snapshot": snap, "wal": wal,
        "findings": [], "ok": True}
    for part in (snap, wal):
        if part["corrupt_at"] is not None:
            report["findings"].append(
                f"{Path(part['path']).name}: corrupt record at offset "
                f"{part['corrupt_at']} ({part['corrupt_detail']}) — a "
                "kvd boot will REFUSE this file (restore from backup "
                "or move it aside for a cold start)")
            report["ok"] = False
    if snap["exists"] and snap["torn_tail_bytes"]:
        # snapshots are written whole + atomically renamed: a torn one
        # means something else scribbled on it
        report["findings"].append(
            f"snapshot.wal has a torn tail of "
            f"{snap['torn_tail_bytes']} byte(s) — snapshots are "
            "atomic-rename artifacts and should never be torn")
        report["ok"] = False
    if wal["torn_tail_bytes"]:
        report["findings"].append(
            f"wal has a torn tail of {wal['torn_tail_bytes']} byte(s) "
            "(normal residue of kill -9 mid-append; the next boot "
            "truncates it loudly)")
    if not snap["exists"] and not wal["exists"]:
        report["findings"].append(
            "no snapshot.wal or wal under the data dir — a respawn "
            "here cold-starts empty")
        report["ok"] = False
    report["replayable_records"] = \
        int(snap["records"]) + int(wal["records"])
    # what a replay would restore, summarized by key class (durable
    # blobs vs queues) — the doctor's "is the durable state actually
    # in there" line. Only computed for clean files.
    if report["ok"]:
        snap_recs = iter_records(dd / "snapshot.wal")
        wal_recs = iter_records(dd / "wal")
        snap_epoch, wal_epoch = _pairing_epochs(snap_recs, wal_recs)
        if snap_epoch and wal_epoch != snap_epoch:
            # same verdict as the boot loader: records already folded
            # into the snapshot — reported, not fatal
            report["findings"].append(
                "wal is unpaired pre-compaction residue (crash "
                "between snapshot rename and WAL truncate); a boot "
                "discards it instead of double-applying")
            report["replayable_records"] = int(snap["records"])
        state = replay_state(data_dir)
        report["restored_keys"] = len(state["kv"])
        report["restored_lists"] = len(state["lists"])
        report["restored_queued_msgs"] = \
            sum(len(v) for v in state["lists"].values())
    return report


def replay_state(data_dir: str) -> Dict[str, Any]:
    """Apply snapshot + WAL records to an in-memory model (the same
    semantics as kv_server.cc's ApplyRecord) and return
    ``{"kv": {key: bytes}, "lists": {key: [bytes]}, "dedup": [ids]}``.
    Raises ValueError on corruption (use :func:`dry_run_replay` for a
    verdict instead of an exception)."""
    dd = Path(data_dir)
    kv: Dict[str, bytes] = {}
    lists: Dict[str, List[bytes]] = {}
    dedup: List[str] = []
    snap_recs = iter_records(dd / "snapshot.wal")
    wal_recs = iter_records(dd / "wal")
    snap_epoch, wal_epoch = _pairing_epochs(snap_recs, wal_recs)
    if snap_epoch and wal_epoch != snap_epoch:
        wal_recs = []  # unpaired pre-compaction residue: boot
        #                discards it (already folded into the snapshot)
    for args in snap_recs + wal_recs:
        _apply(kv, lists, dedup, args)
    return {"kv": kv, "lists": lists, "dedup": dedup}


def _apply(kv: Dict[str, bytes], lists: Dict[str, List[bytes]],
           dedup: List[str], args: List[bytes]) -> None:
    verb = args[0].decode("latin-1").upper()
    key = args[1].decode("latin-1") if len(args) > 1 else ""
    if verb == "SET" and len(args) == 3:
        kv[key] = args[2]
    elif verb == "DEL":
        for k in args[1:]:
            kv.pop(k.decode("latin-1"), None)
            lists.pop(k.decode("latin-1"), None)
    elif verb in ("LPUSH", "RPUSH") and len(args) >= 3:
        dq = lists.setdefault(key, [])
        for v in args[2:]:
            dq.insert(0, v) if verb == "LPUSH" else dq.append(v)
    elif verb in ("LPUSHD", "RPUSHD") and len(args) >= 4:
        dedup.append(args[2].decode("latin-1"))
        dq = lists.setdefault(key, [])
        for v in args[3:]:
            dq.insert(0, v) if verb == "LPUSHD" else dq.append(v)
    elif verb in ("LPOP", "RPOP") and len(args) == 2:
        dq = lists.get(key)
        if dq:
            dq.pop(0) if verb == "LPOP" else dq.pop()
    elif verb == "DEDUP" and len(args) == 2:
        dedup.append(args[1].decode("latin-1"))
    elif verb == "FLUSHALL":
        kv.clear()
        lists.clear()
        dedup.clear()
    # EXPIRE: TTLs re-arm at boot time; the dry run has no clock to
    # judge them against, so they are framing-validated and skipped


__all__ = ["scan_file", "iter_records", "dry_run_replay",
           "replay_state"]
