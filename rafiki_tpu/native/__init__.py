"""Native (C++) data-plane components and their Python clients.

``kv_server.cc`` → ``rafiki-kvd``: the host-side kv/queue server standing
in for the reference deployment's Redis (params + query queues).
"""

from .client import (CLIENT_STATS, KVClient, KVServer, ensure_built,
                     wait_for_server)

__all__ = ["CLIENT_STATS", "KVClient", "KVServer", "ensure_built",
           "wait_for_server"]
