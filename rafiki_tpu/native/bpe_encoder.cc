// Native byte-level BPE chunk encoder (ctypes-loaded shared library).
//
// The serving host path tokenizes every request on CPU before anything
// touches the accelerator; the merge loop (repeatedly find the
// best-ranked adjacent pair, splice) is the hotspot and is pure
// integer work — exactly the kind of runtime component this framework
// keeps native (like the kv data plane, kv_server.cc). The algorithm
// mirrors rafiki_tpu/data/bpe.py::_bpe_chunk token-for-token: same id
// layout (specials, 256 byte ids, one id per merge in training order),
// same lowest-rank-first merge policy, so the Python and native
// encoders are interchangeable (tests assert identity).
//
// C ABI (no pybind11 in this image — loaded via ctypes):
//   rbpe_create(pairs, n_merges) -> handle   (pairs: 2*n_merges int32)
//   rbpe_encode_chunk(handle, bytes, len, out, cap) -> n ids (or -1
//     if cap too small; out never overrun)
//   rbpe_free(handle)

#include <cstddef>
#include <cstdint>
#include <unordered_map>
#include <vector>

using std::size_t;

namespace {

constexpr int32_t kNSpecial = 3;   // PAD/BOS/EOS — bpe.py N_SPECIAL
constexpr int32_t kNBytes = 256;

inline uint64_t pair_key(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

struct Encoder {
  // (left, right) -> merge rank; merge r produces id kNSpecial+kNBytes+r
  std::unordered_map<uint64_t, int32_t> rank;
};

}  // namespace

extern "C" {

void* rbpe_create(const int32_t* pairs, int32_t n_merges) {
  auto* enc = new Encoder();
  enc->rank.reserve(static_cast<size_t>(n_merges) * 2);
  for (int32_t i = 0; i < n_merges; ++i) {
    enc->rank.emplace(pair_key(pairs[2 * i], pairs[2 * i + 1]), i);
  }
  return enc;
}

void rbpe_free(void* handle) { delete static_cast<Encoder*>(handle); }

int32_t rbpe_encode_chunk(void* handle, const uint8_t* chunk,
                          int32_t len, int32_t* out, int32_t cap) {
  const auto* enc = static_cast<Encoder*>(handle);
  if (len > cap) return -1;
  std::vector<int32_t> ids(static_cast<size_t>(len));
  for (int32_t i = 0; i < len; ++i) ids[i] = kNSpecial + chunk[i];

  // classic BPE: repeatedly merge the lowest-ranked adjacent pair.
  // One splice pass per round, exactly like the Python twin — the
  // cost is the integer scan, which is what going native buys back.
  while (ids.size() > 1) {
    int32_t best_rank = INT32_MAX;
    uint64_t best = 0;
    for (size_t i = 0; i + 1 < ids.size(); ++i) {
      auto it = enc->rank.find(pair_key(ids[i], ids[i + 1]));
      if (it != enc->rank.end() && it->second < best_rank) {
        best_rank = it->second;
        best = pair_key(ids[i], ids[i + 1]);
      }
    }
    if (best_rank == INT32_MAX) break;
    const int32_t merged = kNSpecial + kNBytes + best_rank;
    size_t w = 0;
    for (size_t i = 0; i < ids.size();) {
      if (i + 1 < ids.size() && pair_key(ids[i], ids[i + 1]) == best) {
        ids[w++] = merged;
        i += 2;
      } else {
        ids[w++] = ids[i++];
      }
    }
    ids.resize(w);
  }
  if (static_cast<int32_t>(ids.size()) > cap) return -1;
  for (size_t i = 0; i < ids.size(); ++i) out[i] = ids[i];
  return static_cast<int32_t>(ids.size());
}

}  // extern "C"
