// rafiki-kvd — the native kv/queue data-plane server.
//
// Plays the role Redis plays in the reference deployment (SURVEY.md §2
// "Param store" / "Query/prediction queues", §5.8(b)): one small server on
// the TPU-VM host carrying (a) trial parameter blobs and (b) the
// predictor's per-worker query/prediction queues. Speaks a RESP-compatible
// subset so the Python client stays trivial; the implementation is original
// (thread-per-connection, one store mutex, condition variable for blocking
// pops — the right scale for tens of workers on one host, not thousands).
//
// Commands: PING, SET, GET, DEL, EXISTS, KEYS <glob>, INCR,
//           LPUSH, RPUSH, LPUSHD/RPUSHD <key> <dedup_id> <value...>,
//           BRPOP <key...> <timeout_s>, LPOP, RPOP, LLEN,
//           EXPIRE <key> <seconds>, TTL <key>, STATS, COMPACT,
//           FLUSHALL, SHUTDOWN.
//
// EXPIRE delta vs Redis: the TTL survives key deletion/recreation until
// it fires. That is deliberate — the predictor sets a TTL on each
// transient reply queue (q:preds:<query_id>), and a worker's LATE push
// after the gather's discard must not resurrect an immortal key (query
// ids are never reused, so a lingering TTL can only ever collect
// garbage). Without this, every late reply leaked a list forever.
//
// Persistence (--data-dir DIR): every mutating command is appended to an
// append-only WAL of length-prefixed, CRC32-checksummed records, fsynced
// per --fsync policy (always / everysec / no). The WAL is periodically
// compacted into a snapshot (the whole store re-encoded as one batch of
// records, written to a temp file and atomically renamed — the Redis AOF
// rewrite idea), after which the live WAL restarts empty. Boot replays
// snapshot then WAL: a torn tail (incomplete record at EOF — the normal
// residue of kill -9 mid-append) is truncated LOUDLY; a CRC-corrupt
// record with its full length present means disk/operator damage, and
// the server refuses to boot with a structured JSON error on stdout
// (exit 4) rather than serve silently-wrong state.
//
// Deduplicated pushes (LPUSHD/RPUSHD): queue pushes from reconnecting
// clients carry a client-minted dedup id; the server keeps a bounded
// recent-set (also WAL-logged and snapshot-carried, so it survives
// restart) and answers a repeated id with the current queue length
// WITHOUT pushing — a retried push after a connection drop or a server
// respawn never double-delivers.

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable list_cv;  // signalled on any list push
  std::unordered_map<std::string, std::string> kv;
  std::unordered_map<std::string, std::deque<std::string>> lists;
  // key → absolute expiry; purged opportunistically (throttled scan at
  // command dispatch). Only transient queue keys carry TTLs, so the
  // scan is O(outstanding queries), not O(all blobs).
  std::unordered_map<std::string,
                     std::chrono::steady_clock::time_point> ttl;
  // bounded dedup recent-set for LPUSHD/RPUSHD (insertion-ordered
  // eviction)
  std::deque<std::string> dedup_fifo;
  std::unordered_set<std::string> dedup_set;
};

constexpr size_t kDedupCap = 8192;

Store g_store;
std::atomic<bool> g_shutdown{false};
std::atomic<int64_t> g_last_purge_ms{0};
int g_listen_fd = -1;

// live connection fds, force-shutdown on SHUTDOWN so ServeConn threads
// blocked in read() unblock and the process exits promptly instead of
// waiting for every idle client to hang up
std::mutex g_conns_mu;
std::vector<int> g_conn_fds;

void RegisterConn(int fd) {
  std::lock_guard<std::mutex> l(g_conns_mu);
  g_conn_fds.push_back(fd);
}

void UnregisterConn(int fd) {
  std::lock_guard<std::mutex> l(g_conns_mu);
  for (auto it = g_conn_fds.begin(); it != g_conn_fds.end(); ++it)
    if (*it == fd) { g_conn_fds.erase(it); break; }
}

void ShutdownAllConns() {
  std::lock_guard<std::mutex> l(g_conns_mu);
  for (int fd : g_conn_fds) shutdown(fd, SHUT_RDWR);
}

// ---- crc32 (IEEE 802.3 polynomial, table-driven) ---------------------------
uint32_t Crc32(const char* data, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i)
    crc = table[(crc ^ static_cast<unsigned char>(data[i])) & 0xFF] ^
          (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// ---- persistence -----------------------------------------------------------
//
// WAL record framing: [u32 payload_len][u32 crc32(payload)][payload]
// where payload = [u32 nargs] then per arg [u32 len][bytes]. All
// little-endian host order (the WAL never leaves the machine that
// wrote it).

struct Persist {
  bool enabled = false;
  std::string dir;
  int fsync_policy = 1;       // 0 = no, 1 = everysec, 2 = always
  int64_t wal_rotate_bytes = 64LL << 20;
  int wal_fd = -1;
  int64_t wal_bytes = 0;
  int64_t snapshot_bytes = 0;
  std::atomic<bool> dirty{false};
  std::chrono::steady_clock::time_point last_fsync =
      std::chrono::steady_clock::now();
  std::chrono::steady_clock::time_point snapshot_at =
      std::chrono::steady_clock::now();
  bool has_snapshot = false;
  // boot-replay bookkeeping (surfaced via STATS)
  double replay_seconds = 0.0;
  int64_t replayed_records = 0;
  int64_t truncated_bytes = 0;
  int64_t compactions = 0;
  bool in_replay = false;  // replay applies via Execute-side helpers;
  //                          it must never re-log what it reads
  // snapshot/WAL pairing: a snapshot's first record is `EPOCH <id>`
  // and the WAL the SAME compaction reset starts with `WALHDR <id>`.
  // Boot only replays a WAL whose header matches the snapshot's epoch
  // — a crash between the snapshot rename and the WAL truncate leaves
  // the PRE-compaction WAL behind, and replaying it on top of the
  // snapshot that already folded it in would double-deliver every
  // queued message since the previous compaction.
  uint64_t snapshot_epoch = 0;  // expected pairing (0 = no snapshot)
  uint64_t wal_epoch = 0;       // header seen in the WAL (0 = none)
};

Persist g_persist;

std::string WalPath() { return g_persist.dir + "/wal"; }
std::string SnapshotPath() { return g_persist.dir + "/snapshot.wal"; }

void AppendU32(std::string* out, uint32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);
}

std::string EncodeRecord(const std::vector<std::string>& args) {
  std::string payload;
  AppendU32(&payload, static_cast<uint32_t>(args.size()));
  for (const auto& a : args) {
    AppendU32(&payload, static_cast<uint32_t>(a.size()));
    payload += a;
  }
  std::string rec;
  AppendU32(&rec, static_cast<uint32_t>(payload.size()));
  AppendU32(&rec, Crc32(payload.data(), payload.size()));
  rec += payload;
  return rec;
}

bool WriteAllFd(int fd, const char* data, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t w = write(fd, data + sent, n - sent);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    sent += static_cast<size_t>(w);
  }
  return true;
}

void MkdirP(const std::string& path) {
  std::string cur;
  for (size_t i = 0; i <= path.size(); ++i) {
    if (i == path.size() || path[i] == '/') {
      if (!cur.empty()) mkdir(cur.c_str(), 0755);
      if (i < path.size()) cur += '/';
    } else {
      cur += path[i];
    }
  }
}

void FsyncDir(const std::string& dir) {
  int dfd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (dfd >= 0) {
    fsync(dfd);
    close(dfd);
  }
}

// forward decl (compaction re-encodes the whole store)
void CompactLocked();

// Append one mutation record. Caller holds g_store.mu so WAL order is
// exactly application order. Deliberately does NOT rotate: several
// command sites log BEFORE applying (so the record can use the args
// pre-move), and an inline compaction here would snapshot the store
// WITHOUT the pending mutation while truncating the WAL record that
// carries it — a durably lost acknowledged write. Rotation runs via
// MaybeRotateLocked() at the END of each mutating branch, after the
// mutation has landed in the store.
void LogLocked(const std::vector<std::string>& args) {
  if (!g_persist.enabled || g_persist.in_replay) return;
  std::string rec = EncodeRecord(args);
  if (!WriteAllFd(g_persist.wal_fd, rec.data(), rec.size())) {
    // an unwritable WAL means durability is gone: better to die loudly
    // (the supervisor respawns and replays what WAS written) than to
    // keep acking writes that will not survive
    fprintf(stderr, "rafiki-kvd: WAL write failed (%s) — aborting\n",
            strerror(errno));
    _exit(5);
  }
  g_persist.wal_bytes += static_cast<int64_t>(rec.size());
  if (g_persist.fsync_policy == 2) {
    fsync(g_persist.wal_fd);
    g_persist.last_fsync = std::chrono::steady_clock::now();
  } else {
    g_persist.dirty.store(true, std::memory_order_relaxed);
  }
}

// Rotation check — call ONLY after the branch's mutation has been
// applied to the store (see LogLocked).
void MaybeRotateLocked() {
  if (g_persist.enabled && !g_persist.in_replay &&
      g_persist.wal_bytes > g_persist.wal_rotate_bytes)
    CompactLocked();
}

// Re-encode the whole store as one record batch → temp file → fsync →
// atomic rename over snapshot.wal → truncate the live WAL. Caller
// holds g_store.mu (mutations pause for the duration — acceptable at
// this server's scale, and the only way the snapshot is a consistent
// cut without a fork).
//
// Crash-consistency: the snapshot's first record is `EPOCH <id>` (a
// fresh random 64-bit id per compaction — random, not a counter, so
// an id can never repeat across restarts) and the truncated WAL's
// first record is `WALHDR <id>`. A crash between the rename and the
// truncate leaves the new snapshot next to the PRE-compaction WAL —
// whose header (if any) names a DIFFERENT epoch, so the next boot
// discards it instead of double-applying records the snapshot already
// folded in.
void CompactLocked() {
  if (!g_persist.enabled) return;
  std::string tmp = SnapshotPath() + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) {
    fprintf(stderr, "rafiki-kvd: cannot write snapshot %s: %s\n",
            tmp.c_str(), strerror(errno));
    return;  // keep the WAL growing — durable, just not compact
  }
  std::string buf;
  auto flush = [&]() -> bool {
    if (buf.empty()) return true;
    bool ok = WriteAllFd(fd, buf.data(), buf.size());
    buf.clear();
    return ok;
  };
  bool ok = true;
  int64_t bytes = 0;
  auto add = [&](const std::vector<std::string>& args) {
    std::string rec = EncodeRecord(args);
    bytes += static_cast<int64_t>(rec.size());
    buf += rec;
    if (buf.size() > (1u << 20)) ok = ok && flush();
  };
  uint64_t epoch = 0;
  {
    FILE* ur = fopen("/dev/urandom", "rb");
    if (ur != nullptr) {
      if (fread(&epoch, sizeof(epoch), 1, ur) != 1) epoch = 0;
      fclose(ur);
    }
    if (epoch == 0)  // urandom unavailable: clock ticks still never
      epoch = static_cast<uint64_t>(  // repeat across restarts
          std::chrono::steady_clock::now().time_since_epoch().count())
          ^ (static_cast<uint64_t>(getpid()) << 48);
  }
  add({"EPOCH", std::to_string(epoch)});
  for (const auto& [k, v] : g_store.kv) add({"SET", k, v});
  for (const auto& [k, dq] : g_store.lists) {
    if (dq.empty()) continue;
    std::vector<std::string> rec = {"RPUSH", k};
    for (const auto& v : dq) rec.push_back(v);
    add(rec);
  }
  auto now = std::chrono::steady_clock::now();
  for (const auto& [k, dl] : g_store.ttl) {
    double remain =
        std::chrono::duration<double>(dl - now).count();
    if (remain < 0.0) remain = 0.0;
    add({"EXPIRE", k, std::to_string(remain)});
  }
  for (const auto& id : g_store.dedup_fifo) add({"DEDUP", id});
  ok = ok && flush();
  ok = ok && fsync(fd) == 0;
  close(fd);
  if (!ok) {
    fprintf(stderr, "rafiki-kvd: snapshot write failed: %s\n",
            strerror(errno));
    unlink(tmp.c_str());
    return;
  }
  if (rename(tmp.c_str(), SnapshotPath().c_str()) != 0) {
    fprintf(stderr, "rafiki-kvd: snapshot rename failed: %s\n",
            strerror(errno));
    unlink(tmp.c_str());
    return;
  }
  FsyncDir(g_persist.dir);
  // snapshot durable: the WAL restarts with the pairing header
  if (g_persist.wal_fd >= 0) close(g_persist.wal_fd);
  g_persist.wal_fd =
      open(WalPath().c_str(), O_WRONLY | O_CREAT | O_TRUNC | O_APPEND,
           0644);
  if (g_persist.wal_fd < 0) {
    fprintf(stderr, "rafiki-kvd: cannot reopen WAL after compaction: "
            "%s — aborting\n", strerror(errno));
    _exit(5);
  }
  std::string hdr = EncodeRecord({"WALHDR", std::to_string(epoch)});
  if (!WriteAllFd(g_persist.wal_fd, hdr.data(), hdr.size())) {
    fprintf(stderr, "rafiki-kvd: cannot write WAL header after "
            "compaction: %s — aborting\n", strerror(errno));
    _exit(5);
  }
  fsync(g_persist.wal_fd);
  g_persist.snapshot_epoch = epoch;
  g_persist.wal_epoch = epoch;
  g_persist.wal_bytes = static_cast<int64_t>(hdr.size());
  g_persist.snapshot_bytes = bytes;
  g_persist.has_snapshot = true;
  g_persist.snapshot_at = std::chrono::steady_clock::now();
  g_persist.last_fsync = g_persist.snapshot_at;
  g_persist.compactions += 1;
}

// ---- replay ----------------------------------------------------------------

void NoteDedupLocked(const std::string& id) {
  if (g_store.dedup_set.insert(id).second) {
    g_store.dedup_fifo.push_back(id);
    while (g_store.dedup_fifo.size() > kDedupCap) {
      g_store.dedup_set.erase(g_store.dedup_fifo.front());
      g_store.dedup_fifo.pop_front();
    }
  }
}

void ArmTtlLocked(const std::string& key, double secs) {
  g_store.ttl[key] =
      std::chrono::steady_clock::now() +
      std::chrono::duration_cast<std::chrono::steady_clock::duration>(
          std::chrono::duration<double>(secs));
}

// Apply one already-decoded record to the store (no logging, no
// locking — replay runs single-threaded before the listener starts).
// Returns false for a record that cannot be applied (unknown verb =
// a WAL from a newer server, refuse rather than half-replay).
bool ApplyRecord(const std::vector<std::string>& args) {
  if (args.empty()) return false;
  std::string cmd = args[0];
  for (auto& c : cmd) c = static_cast<char>(toupper(c));
  if (cmd == "SET" && args.size() == 3) {
    g_store.kv[args[1]] = args[2];
    return true;
  }
  if (cmd == "DEL" && args.size() >= 2) {
    for (size_t i = 1; i < args.size(); ++i) {
      g_store.kv.erase(args[i]);
      g_store.lists.erase(args[i]);
    }
    return true;
  }
  if ((cmd == "LPUSH" || cmd == "RPUSH") && args.size() >= 3) {
    auto& dq = g_store.lists[args[1]];
    for (size_t i = 2; i < args.size(); ++i) {
      if (cmd == "LPUSH") dq.push_front(args[i]);
      else dq.push_back(args[i]);
    }
    return true;
  }
  if ((cmd == "LPUSHD" || cmd == "RPUSHD") && args.size() >= 4) {
    NoteDedupLocked(args[2]);
    auto& dq = g_store.lists[args[1]];
    for (size_t i = 3; i < args.size(); ++i) {
      if (cmd == "LPUSHD") dq.push_front(args[i]);
      else dq.push_back(args[i]);
    }
    return true;
  }
  if ((cmd == "LPOP" || cmd == "RPOP") && args.size() == 2) {
    auto it = g_store.lists.find(args[1]);
    if (it != g_store.lists.end() && !it->second.empty()) {
      if (cmd == "LPOP") it->second.pop_front();
      else it->second.pop_back();
    }
    return true;
  }
  if (cmd == "EXPIRE" && args.size() == 3) {
    ArmTtlLocked(args[1], strtod(args[2].c_str(), nullptr));
    return true;
  }
  if (cmd == "DEDUP" && args.size() == 2) {
    NoteDedupLocked(args[1]);
    return true;
  }
  if (cmd == "FLUSHALL") {
    g_store.kv.clear();
    g_store.lists.clear();
    g_store.ttl.clear();
    g_store.dedup_fifo.clear();
    g_store.dedup_set.clear();
    return true;
  }
  if (cmd == "EPOCH" && args.size() == 2) {
    g_persist.snapshot_epoch = strtoull(args[1].c_str(), nullptr, 10);
    return true;
  }
  if (cmd == "WALHDR" && args.size() == 2) {
    g_persist.wal_epoch = strtoull(args[1].c_str(), nullptr, 10);
    return true;
  }
  return false;
}

uint32_t ReadU32(const std::string& buf, size_t off) {
  uint32_t v;
  memcpy(&v, buf.data() + off, 4);
  return v;
}

// Replay one persistence file. Returns false on CRC corruption (boot
// must fail); a torn tail is truncated in place and reported.
bool ReplayFile(const std::string& path, bool truncate_torn) {
  FILE* f = fopen(path.c_str(), "rb");
  if (f == nullptr) return true;  // absent = nothing to replay
  std::string buf;
  char chunk[1 << 16];
  size_t n;
  while ((n = fread(chunk, 1, sizeof(chunk), f)) > 0) buf.append(chunk, n);
  fclose(f);
  size_t off = 0;
  while (off < buf.size()) {
    if (off + 8 > buf.size()) break;  // torn header
    uint32_t len = ReadU32(buf, off);
    if (len > (1u << 30)) {
      // an absurd length is indistinguishable from scribbled-over
      // framing: corruption, not a torn append
      fprintf(stdout,
              "{\"error\": \"kvd_wal_corrupt\", \"file\": \"%s\", "
              "\"offset\": %zu, \"detail\": \"record length %u "
              "exceeds 1GiB bound\"}\n",
              path.c_str(), off, len);
      return false;
    }
    if (off + 8 + len > buf.size()) break;  // torn payload
    uint32_t crc = ReadU32(buf, off + 4);
    if (Crc32(buf.data() + off + 8, len) != crc) {
      fprintf(stdout,
              "{\"error\": \"kvd_wal_corrupt\", \"file\": \"%s\", "
              "\"offset\": %zu, \"detail\": \"crc mismatch\"}\n",
              path.c_str(), off);
      return false;
    }
    // decode args
    std::vector<std::string> args;
    size_t p = off + 8;
    size_t end = off + 8 + len;
    bool ok = len >= 4;
    if (ok) {
      uint32_t nargs = ReadU32(buf, p);
      p += 4;
      for (uint32_t i = 0; i < nargs && ok; ++i) {
        if (p + 4 > end) { ok = false; break; }
        uint32_t alen = ReadU32(buf, p);
        p += 4;
        if (p + alen > end) { ok = false; break; }
        args.emplace_back(buf.data() + p, alen);
        p += alen;
      }
    }
    if (!ok || !ApplyRecord(args)) {
      fprintf(stdout,
              "{\"error\": \"kvd_wal_corrupt\", \"file\": \"%s\", "
              "\"offset\": %zu, \"detail\": \"undecodable record\"}\n",
              path.c_str(), off);
      return false;
    }
    g_persist.replayed_records += 1;
    off += 8 + len;
  }
  if (off < buf.size()) {
    // torn tail: the normal residue of kill -9 mid-append. Truncate
    // LOUDLY — the lost suffix was never acknowledged as durable
    // under any fsync policy weaker than the crash.
    fprintf(stderr,
            "rafiki-kvd: truncating torn tail of %s: %zu byte(s) "
            "past the last complete record at offset %zu\n",
            path.c_str(), buf.size() - off, off);
    g_persist.truncated_bytes +=
        static_cast<int64_t>(buf.size() - off);
    if (truncate_torn) {
      if (truncate(path.c_str(), static_cast<off_t>(off)) != 0)
        fprintf(stderr, "rafiki-kvd: truncate(%s) failed: %s\n",
                path.c_str(), strerror(errno));
    }
  }
  return true;
}

// Decode the WAL's first record WITHOUT applying it; returns its
// WALHDR epoch, or 0 when the file is absent/empty/not-a-header (the
// gating caller treats 0 as "unpaired").
uint64_t PeekWalEpoch(const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (f == nullptr) return 0;
  char hdr[8];
  uint64_t out = 0;
  std::string payload;
  do {
    if (fread(hdr, 1, 8, f) != 8) break;
    uint32_t len, crc;
    memcpy(&len, hdr, 4);
    memcpy(&crc, hdr + 4, 4);
    if (len < 8 || len > 256) break;  // WALHDR records are tiny
    payload.resize(len);
    if (fread(payload.data(), 1, len, f) != len) break;
    if (Crc32(payload.data(), len) != crc) break;
    uint32_t nargs, a0len;
    memcpy(&nargs, payload.data(), 4);
    memcpy(&a0len, payload.data() + 4, 4);
    if (nargs != 2 || a0len != 6 ||
        payload.compare(8, 6, "WALHDR") != 0)
      break;
    uint32_t a1len;
    memcpy(&a1len, payload.data() + 14, 4);
    if (18 + a1len > len) break;
    out = strtoull(payload.substr(18, a1len).c_str(), nullptr, 10);
  } while (false);
  fclose(f);
  return out;
}

// Returns false when boot must fail (corrupt records).
bool LoadPersisted() {
  auto t0 = std::chrono::steady_clock::now();
  g_persist.in_replay = true;
  struct stat st;
  if (stat(SnapshotPath().c_str(), &st) == 0) {
    g_persist.snapshot_bytes = st.st_size;
    g_persist.has_snapshot = true;
    g_persist.snapshot_at = std::chrono::steady_clock::now();
    if (!ReplayFile(SnapshotPath(), /*truncate_torn=*/false))
      return false;
  }
  bool wal_paired = true;
  if (g_persist.snapshot_epoch != 0 &&
      PeekWalEpoch(WalPath()) != g_persist.snapshot_epoch) {
    // the WAL does not belong to this snapshot: a crash landed
    // between the snapshot rename and the WAL truncate, so every
    // record in it is ALREADY folded into the snapshot — replaying
    // would double-deliver. Discard it loudly.
    wal_paired = false;
    if (stat(WalPath().c_str(), &st) == 0 && st.st_size > 0) {
      fprintf(stderr,
              "rafiki-kvd: discarding stale pre-compaction WAL "
              "(%lld byte(s), unpaired with snapshot epoch %llu) — "
              "its records are already in the snapshot\n",
              static_cast<long long>(st.st_size),
              static_cast<unsigned long long>(
                  g_persist.snapshot_epoch));
      g_persist.truncated_bytes += st.st_size;
      if (truncate(WalPath().c_str(), 0) != 0) {
        fprintf(stdout,
                "{\"error\": \"kvd_wal_unwritable\", \"file\": "
                "\"%s\", \"detail\": \"cannot discard stale WAL: "
                "%s\"}\n",
                WalPath().c_str(), strerror(errno));
        return false;
      }
    }
  }
  if (wal_paired &&
      !ReplayFile(WalPath(), /*truncate_torn=*/true))
    return false;
  g_persist.in_replay = false;
  if (stat(WalPath().c_str(), &st) == 0)
    g_persist.wal_bytes = st.st_size;
  g_persist.wal_fd =
      open(WalPath().c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (g_persist.wal_fd < 0) {
    fprintf(stdout,
            "{\"error\": \"kvd_wal_unwritable\", \"file\": \"%s\", "
            "\"detail\": \"%s\"}\n",
            WalPath().c_str(), strerror(errno));
    return false;
  }
  if (g_persist.snapshot_epoch != 0 &&
      g_persist.wal_epoch != g_persist.snapshot_epoch) {
    // discarded-stale or crashed-before-header case: re-pair the live
    // WAL with the snapshot NOW, or the records appended from here on
    // would themselves read as unpaired at the next boot
    std::string rec = EncodeRecord(
        {"WALHDR", std::to_string(g_persist.snapshot_epoch)});
    if (!WriteAllFd(g_persist.wal_fd, rec.data(), rec.size())) {
      fprintf(stdout,
              "{\"error\": \"kvd_wal_unwritable\", \"file\": \"%s\", "
              "\"detail\": \"cannot write pairing header: %s\"}\n",
              WalPath().c_str(), strerror(errno));
      return false;
    }
    fsync(g_persist.wal_fd);
    g_persist.wal_epoch = g_persist.snapshot_epoch;
    g_persist.wal_bytes += static_cast<int64_t>(rec.size());
  }
  g_persist.replay_seconds =
      std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                    t0)
          .count();
  if (g_persist.replayed_records > 0)
    fprintf(stderr,
            "rafiki-kvd: replayed %lld record(s) in %.3fs "
            "(%lld truncated byte(s))\n",
            static_cast<long long>(g_persist.replayed_records),
            g_persist.replay_seconds,
            static_cast<long long>(g_persist.truncated_bytes));
  return true;
}

void FsyncLoop() {
  int ticks = 0;
  while (!g_shutdown.load()) {
    // 100ms ticks so process exit never waits out a full second, but
    // the fsync itself still runs at the policy's 1s cadence
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    if (++ticks < 10) continue;
    ticks = 0;
    if (g_persist.dirty.exchange(false, std::memory_order_relaxed)) {
      // fsync OUTSIDE g_store.mu: a slow-disk fsync must not pause
      // every command for its duration. dup() under the lock pins the
      // same open file description, so a concurrent compaction
      // swapping wal_fd can't invalidate the fd mid-fsync (flushing
      // the pre-compaction file late is harmless — compaction fsyncs
      // its replacement itself).
      int dupfd = -1;
      {
        std::lock_guard<std::mutex> l(g_store.mu);
        if (g_persist.wal_fd >= 0) dupfd = dup(g_persist.wal_fd);
      }
      if (dupfd >= 0) {
        fsync(dupfd);
        close(dupfd);
        std::lock_guard<std::mutex> l(g_store.mu);
        g_persist.last_fsync = std::chrono::steady_clock::now();
      }
    }
  }
}

void PurgeExpiredLocked() {
  auto now = std::chrono::steady_clock::now();
  for (auto it = g_store.ttl.begin(); it != g_store.ttl.end();) {
    if (it->second <= now) {
      g_store.kv.erase(it->first);
      g_store.lists.erase(it->first);
      it = g_store.ttl.erase(it);
    } else {
      ++it;
    }
  }
}

void MaybePurgeExpired() {
  // throttle the scan: correctness only needs eventual collection
  int64_t now_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                       std::chrono::steady_clock::now().time_since_epoch())
                       .count();
  int64_t last = g_last_purge_ms.load(std::memory_order_relaxed);
  if (now_ms - last < 50) return;
  if (!g_last_purge_ms.compare_exchange_strong(last, now_ms)) return;
  std::lock_guard<std::mutex> l(g_store.mu);
  PurgeExpiredLocked();
}

// ---- glob match (supports * and ?) ----------------------------------------
bool GlobMatch(const char* p, const char* s) {
  for (; *p; ++p, ++s) {
    if (*p == '*') {
      while (*(p + 1) == '*') ++p;
      for (const char* t = s + strlen(s); t >= s; --t)
        if (GlobMatch(p + 1, t)) return true;
      return false;
    }
    if (*s == '\0' || (*p != '?' && *p != *s)) return false;
  }
  return *s == '\0';
}

// ---- socket io ------------------------------------------------------------
bool ReadN(int fd, char* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = read(fd, buf + got, n - got);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

bool ReadLine(int fd, std::string* out) {
  // RESP lines are short (headers only); read byte-wise up to CRLF.
  out->clear();
  char c;
  while (true) {
    if (!ReadN(fd, &c, 1)) return false;
    if (c == '\r') {
      if (!ReadN(fd, &c, 1) || c != '\n') return false;
      return true;
    }
    out->push_back(c);
    if (out->size() > 1 << 16) return false;  // header bomb guard
  }
}

bool WriteAll(int fd, const std::string& data) {
  size_t sent = 0;
  while (sent < data.size()) {
    ssize_t w = write(fd, data.data() + sent, data.size() - sent);
    if (w <= 0) return false;
    sent += static_cast<size_t>(w);
  }
  return true;
}

std::string Bulk(const std::string& s) {
  return "$" + std::to_string(s.size()) + "\r\n" + s + "\r\n";
}
const std::string kNil = "$-1\r\n";
const std::string kNilArray = "*-1\r\n";
std::string Int(long long v) { return ":" + std::to_string(v) + "\r\n"; }
std::string Err(const std::string& m) { return "-ERR " + m + "\r\n"; }

std::string StatsReply() {
  std::lock_guard<std::mutex> l(g_store.mu);
  auto now = std::chrono::steady_clock::now();
  auto age = [&](std::chrono::steady_clock::time_point t) {
    return std::chrono::duration<double>(now - t).count();
  };
  const char* pol = g_persist.fsync_policy == 2   ? "always"
                    : g_persist.fsync_policy == 1 ? "everysec"
                                                  : "no";
  char line[256];
  std::string out;
  auto addi = [&](const char* k, long long v) {
    snprintf(line, sizeof(line), "%s %lld\n", k, v);
    out += line;
  };
  auto addf = [&](const char* k, double v) {
    snprintf(line, sizeof(line), "%s %.6f\n", k, v);
    out += line;
  };
  addi("persist_enabled", g_persist.enabled ? 1 : 0);
  out += std::string("fsync_policy ") + pol + "\n";
  addi("wal_bytes", g_persist.wal_bytes);
  addi("snapshot_bytes", g_persist.snapshot_bytes);
  addf("snapshot_age_s",
       g_persist.has_snapshot ? age(g_persist.snapshot_at) : -1.0);
  addf("last_fsync_age_s",
       g_persist.enabled ? age(g_persist.last_fsync) : -1.0);
  addf("replay_seconds", g_persist.replay_seconds);
  addi("replayed_records", g_persist.replayed_records);
  addi("wal_truncated_bytes", g_persist.truncated_bytes);
  addi("compactions", g_persist.compactions);
  addi("dedup_ids", static_cast<long long>(g_store.dedup_fifo.size()));
  addi("keys", static_cast<long long>(g_store.kv.size()));
  addi("lists", static_cast<long long>(g_store.lists.size()));
  return Bulk(out);
}

// ---- command dispatch ------------------------------------------------------
std::string Execute(std::vector<std::string>& args) {
  std::string cmd = args[0];
  for (auto& c : cmd) c = static_cast<char>(toupper(c));
  MaybePurgeExpired();

  if (cmd == "PING") return "+PONG\r\n";
  if (cmd == "SHUTDOWN") {
    {
      // make everything acknowledged so far durable before the
      // graceful exit (kill -9 skips this path by definition)
      std::lock_guard<std::mutex> l(g_store.mu);
      if (g_persist.enabled && g_persist.wal_fd >= 0) {
        fsync(g_persist.wal_fd);
        g_persist.last_fsync = std::chrono::steady_clock::now();
      }
    }
    g_shutdown.store(true);
    if (g_listen_fd >= 0) shutdown(g_listen_fd, SHUT_RDWR);
    ShutdownAllConns();
    return "+OK\r\n";
  }
  if (cmd == "STATS" || cmd == "INFO") return StatsReply();
  if (cmd == "COMPACT") {
    std::lock_guard<std::mutex> l(g_store.mu);
    if (!g_persist.enabled) return Err("no --data-dir configured");
    CompactLocked();
    return "+OK\r\n";
  }
  if (cmd == "FLUSHALL") {
    std::lock_guard<std::mutex> l(g_store.mu);
    g_store.kv.clear();
    g_store.lists.clear();
    g_store.ttl.clear();
    g_store.dedup_fifo.clear();
    g_store.dedup_set.clear();
    LogLocked({"FLUSHALL"});
    MaybeRotateLocked();
    return "+OK\r\n";
  }
  if (cmd == "TTL" && args.size() == 2) {
    // redis semantics: -2 missing key, -1 no expiry, else seconds left
    // (rounded UP, like redis). A key DEL'd while its TTL survives
    // (the kvd reply-queue deviation) reports -2 here — the armed TTL
    // is an internal condemnation, not key liveness.
    std::lock_guard<std::mutex> l(g_store.mu);
    bool exists = g_store.kv.count(args[1]) || g_store.lists.count(args[1]);
    if (!exists) return Int(-2);
    auto it = g_store.ttl.find(args[1]);
    if (it == g_store.ttl.end()) return Int(-1);
    auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                  it->second - std::chrono::steady_clock::now())
                  .count();
    return Int(ms <= 0 ? 0 : (ms + 999) / 1000);
  }
  if (cmd == "EXPIRE" && args.size() == 3) {
    double secs = strtod(args[2].c_str(), nullptr);
    std::lock_guard<std::mutex> l(g_store.mu);
    // unlike Redis, the key need not exist yet: the predictor arms the
    // TTL when it ISSUES a query, so even a reply that arrives after
    // the gather's discard is already condemned
    ArmTtlLocked(args[1], secs);
    LogLocked({"EXPIRE", args[1], args[2]});
    MaybeRotateLocked();
    return Int(1);
  }
  if (cmd == "SET" && args.size() == 3) {
    std::lock_guard<std::mutex> l(g_store.mu);
    LogLocked({"SET", args[1], args[2]});
    g_store.kv[args[1]] = std::move(args[2]);
    MaybeRotateLocked();
    return "+OK\r\n";
  }
  if (cmd == "GET" && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    auto it = g_store.kv.find(args[1]);
    return it == g_store.kv.end() ? kNil : Bulk(it->second);
  }
  if (cmd == "DEL" && args.size() >= 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    long long n = 0;
    for (size_t i = 1; i < args.size(); ++i) {
      n += g_store.kv.erase(args[i]);
      n += g_store.lists.erase(args[i]);
    }
    if (n > 0) {
      LogLocked(args);
      MaybeRotateLocked();
    }
    return Int(n);
  }
  if (cmd == "EXISTS" && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    return Int(g_store.kv.count(args[1]) || g_store.lists.count(args[1]));
  }
  if (cmd == "KEYS" && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    std::string out;
    long long n = 0;
    for (auto& [k, _] : g_store.kv)
      if (GlobMatch(args[1].c_str(), k.c_str())) { out += Bulk(k); ++n; }
    for (auto& [k, _] : g_store.lists)
      if (GlobMatch(args[1].c_str(), k.c_str())) { out += Bulk(k); ++n; }
    return "*" + std::to_string(n) + "\r\n" + out;
  }
  if (cmd == "INCR" && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    auto& v = g_store.kv[args[1]];
    long long n = v.empty() ? 0 : strtoll(v.c_str(), nullptr, 10);
    v = std::to_string(n + 1);
    // logged as SET-of-result: replaying an INCR record twice (or
    // against a snapshot that already holds the result) must not
    // double-count
    LogLocked({"SET", args[1], v});
    MaybeRotateLocked();
    return Int(n + 1);
  }
  if ((cmd == "LPUSH" || cmd == "RPUSH") && args.size() >= 3) {
    std::lock_guard<std::mutex> l(g_store.mu);
    LogLocked(args);
    auto& dq = g_store.lists[args[1]];
    for (size_t i = 2; i < args.size(); ++i) {
      if (cmd == "LPUSH") dq.push_front(std::move(args[i]));
      else dq.push_back(std::move(args[i]));
    }
    g_store.list_cv.notify_all();
    MaybeRotateLocked();
    return Int(static_cast<long long>(dq.size()));
  }
  if ((cmd == "LPUSHD" || cmd == "RPUSHD") && args.size() >= 4) {
    // deduplicated push: <key> <dedup_id> <value...>. A repeated id
    // (client retry after a connection drop / server respawn) answers
    // with the current length WITHOUT pushing or logging.
    std::lock_guard<std::mutex> l(g_store.mu);
    auto& dq = g_store.lists[args[1]];
    if (g_store.dedup_set.count(args[2]))
      return Int(static_cast<long long>(dq.size()));
    LogLocked(args);
    NoteDedupLocked(args[2]);
    for (size_t i = 3; i < args.size(); ++i) {
      if (cmd == "LPUSHD") dq.push_front(std::move(args[i]));
      else dq.push_back(std::move(args[i]));
    }
    g_store.list_cv.notify_all();
    MaybeRotateLocked();
    return Int(static_cast<long long>(dq.size()));
  }
  if ((cmd == "LPOP" || cmd == "RPOP") && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    auto it = g_store.lists.find(args[1]);
    if (it == g_store.lists.end() || it->second.empty()) return kNil;
    std::string v;
    if (cmd == "LPOP") {
      v = std::move(it->second.front());
      it->second.pop_front();
    } else {
      v = std::move(it->second.back());
      it->second.pop_back();
    }
    LogLocked({cmd, args[1]});
    MaybeRotateLocked();
    return Bulk(v);
  }
  if (cmd == "LLEN" && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    auto it = g_store.lists.find(args[1]);
    return Int(it == g_store.lists.end()
                   ? 0
                   : static_cast<long long>(it->second.size()));
  }
  if (cmd == "BRPOP" && args.size() >= 3) {
    // BRPOP key [key...] timeout_seconds — pops the *tail* of the first
    // non-empty key; replies *2 [key, value] or nil-array on timeout.
    double timeout_s = strtod(args.back().c_str(), nullptr);
    std::vector<std::string> keys(args.begin() + 1, args.end() - 1);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                        std::chrono::duration<double>(timeout_s));
    std::unique_lock<std::mutex> l(g_store.mu);
    auto try_pop = [&](std::string* out) -> bool {
      for (auto& k : keys) {
        auto it = g_store.lists.find(k);
        if (it != g_store.lists.end() && !it->second.empty()) {
          std::string v = std::move(it->second.back());
          it->second.pop_back();
          LogLocked({"RPOP", k});  // the pop is the mutation; replay
          //                          must not re-deliver it
          MaybeRotateLocked();
          *out = "*2\r\n" + Bulk(k) + Bulk(v);
          return true;
        }
      }
      return false;
    };
    std::string reply;
    while (true) {
      if (try_pop(&reply)) return reply;
      if (g_shutdown.load()) return kNilArray;
      if (timeout_s <= 0) {  // 0 = wait forever (redis semantics)
        g_store.list_cv.wait_for(l, std::chrono::milliseconds(100));
      } else {
        if (g_store.list_cv.wait_until(l, deadline) ==
            std::cv_status::timeout) {
          // re-check once after timeout, then give up
          if (try_pop(&reply)) return reply;
          return kNilArray;
        }
      }
    }
  }
  return Err("unknown command or wrong arity: " + cmd);
}

void ServeConn(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  RegisterConn(fd);
  std::string line;
  while (!g_shutdown.load()) {
    if (!ReadLine(fd, &line) || line.empty() || line[0] != '*') break;
    long n = strtol(line.c_str() + 1, nullptr, 10);
    if (n <= 0 || n > 1 << 20) break;
    std::vector<std::string> args;
    args.reserve(static_cast<size_t>(n));
    bool ok = true;
    for (long i = 0; i < n && ok; ++i) {
      if (!ReadLine(fd, &line) || line.empty() || line[0] != '$') {
        ok = false;
        break;
      }
      long len = strtol(line.c_str() + 1, nullptr, 10);
      if (len < 0 || len > (1L << 31)) { ok = false; break; }
      std::string payload(static_cast<size_t>(len), '\0');
      if (!ReadN(fd, payload.data(), static_cast<size_t>(len))) {
        ok = false;
        break;
      }
      char crlf[2];
      if (!ReadN(fd, crlf, 2)) { ok = false; break; }
      args.push_back(std::move(payload));
    }
    if (!ok || args.empty()) break;
    if (!WriteAll(fd, Execute(args))) break;
  }
  UnregisterConn(fd);
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = 6399;
  const char* host = "127.0.0.1";
  for (int i = 1; i < argc - 1; ++i) {
    if (!strcmp(argv[i], "--port")) port = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--host")) host = argv[i + 1];
    if (!strcmp(argv[i], "--data-dir")) g_persist.dir = argv[i + 1];
    if (!strcmp(argv[i], "--wal-rotate-bytes"))
      g_persist.wal_rotate_bytes = strtoll(argv[i + 1], nullptr, 10);
    if (!strcmp(argv[i], "--fsync")) {
      std::string p = argv[i + 1];
      if (p == "always") g_persist.fsync_policy = 2;
      else if (p == "everysec") g_persist.fsync_policy = 1;
      else if (p == "no") g_persist.fsync_policy = 0;
      else {
        fprintf(stderr, "rafiki-kvd: bad --fsync %s "
                "(always|everysec|no)\n", p.c_str());
        return 2;
      }
    }
  }
  signal(SIGPIPE, SIG_IGN);

  std::thread fsync_thread;
  if (!g_persist.dir.empty()) {
    g_persist.enabled = true;
    MkdirP(g_persist.dir);
    if (!LoadPersisted()) {
      // the structured JSON error is already on stdout: a corrupt WAL
      // must fail the boot, not silently serve wrong state
      fflush(stdout);
      return 4;
    }
    // the everysec fsync thread starts only after listen() succeeds
    // below: a bind failure's `return 1` with a joinable thread would
    // std::terminate instead of exiting cleanly for the supervisor
  }

  g_listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(g_listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (bind(g_listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
      0) {
    perror("bind");
    return 1;
  }
  // port 0 → kernel-assigned; report the real one for the spawner
  socklen_t alen = sizeof(addr);
  getsockname(g_listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  if (listen(g_listen_fd, 128) < 0) {
    perror("listen");
    return 1;
  }
  if (g_persist.enabled && g_persist.fsync_policy == 1)
    fsync_thread = std::thread(FsyncLoop);
  fprintf(stdout, "rafiki-kvd listening on %s:%d\n", host,
          ntohs(addr.sin_port));
  fflush(stdout);

  std::vector<std::thread> conns;
  while (!g_shutdown.load()) {
    int fd = accept(g_listen_fd, nullptr, nullptr);
    if (fd < 0) break;
    conns.emplace_back(ServeConn, fd);
  }
  g_store.list_cv.notify_all();
  close(g_listen_fd);
  for (auto& t : conns)
    if (t.joinable()) t.join();
  if (fsync_thread.joinable()) fsync_thread.join();
  {
    std::lock_guard<std::mutex> l(g_store.mu);
    if (g_persist.enabled && g_persist.wal_fd >= 0) {
      fsync(g_persist.wal_fd);
      close(g_persist.wal_fd);
    }
  }
  return 0;
}
