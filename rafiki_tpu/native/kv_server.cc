// rafiki-kvd — the native kv/queue data-plane server.
//
// Plays the role Redis plays in the reference deployment (SURVEY.md §2
// "Param store" / "Query/prediction queues", §5.8(b)): one small server on
// the TPU-VM host carrying (a) trial parameter blobs and (b) the
// predictor's per-worker query/prediction queues. Speaks a RESP-compatible
// subset so the Python client stays trivial; the implementation is original
// (thread-per-connection, one store mutex, condition variable for blocking
// pops — the right scale for tens of workers on one host, not thousands).
//
// Commands: PING, SET, GET, DEL, EXISTS, KEYS <glob>, INCR,
//           LPUSH, RPUSH, BRPOP <key...> <timeout_s>, LPOP, LLEN,
//           EXPIRE <key> <seconds>, TTL <key>, FLUSHALL, SHUTDOWN.
//
// EXPIRE delta vs Redis: the TTL survives key deletion/recreation until
// it fires. That is deliberate — the predictor sets a TTL on each
// transient reply queue (q:preds:<query_id>), and a worker's LATE push
// after the gather's discard must not resurrect an immortal key (query
// ids are never reused, so a lingering TTL can only ever collect
// garbage). Without this, every late reply leaked a list forever.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable list_cv;  // signalled on any list push
  std::unordered_map<std::string, std::string> kv;
  std::unordered_map<std::string, std::deque<std::string>> lists;
  // key → absolute expiry; purged opportunistically (throttled scan at
  // command dispatch). Only transient queue keys carry TTLs, so the
  // scan is O(outstanding queries), not O(all blobs).
  std::unordered_map<std::string,
                     std::chrono::steady_clock::time_point> ttl;
};

Store g_store;
std::atomic<bool> g_shutdown{false};
std::atomic<int64_t> g_last_purge_ms{0};
int g_listen_fd = -1;

void PurgeExpiredLocked() {
  auto now = std::chrono::steady_clock::now();
  for (auto it = g_store.ttl.begin(); it != g_store.ttl.end();) {
    if (it->second <= now) {
      g_store.kv.erase(it->first);
      g_store.lists.erase(it->first);
      it = g_store.ttl.erase(it);
    } else {
      ++it;
    }
  }
}

void MaybePurgeExpired() {
  // throttle the scan: correctness only needs eventual collection
  int64_t now_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                       std::chrono::steady_clock::now().time_since_epoch())
                       .count();
  int64_t last = g_last_purge_ms.load(std::memory_order_relaxed);
  if (now_ms - last < 50) return;
  if (!g_last_purge_ms.compare_exchange_strong(last, now_ms)) return;
  std::lock_guard<std::mutex> l(g_store.mu);
  PurgeExpiredLocked();
}

// ---- glob match (supports * and ?) ----------------------------------------
bool GlobMatch(const char* p, const char* s) {
  for (; *p; ++p, ++s) {
    if (*p == '*') {
      while (*(p + 1) == '*') ++p;
      for (const char* t = s + strlen(s); t >= s; --t)
        if (GlobMatch(p + 1, t)) return true;
      return false;
    }
    if (*s == '\0' || (*p != '?' && *p != *s)) return false;
  }
  return *s == '\0';
}

// ---- socket io ------------------------------------------------------------
bool ReadN(int fd, char* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = read(fd, buf + got, n - got);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

bool ReadLine(int fd, std::string* out) {
  // RESP lines are short (headers only); read byte-wise up to CRLF.
  out->clear();
  char c;
  while (true) {
    if (!ReadN(fd, &c, 1)) return false;
    if (c == '\r') {
      if (!ReadN(fd, &c, 1) || c != '\n') return false;
      return true;
    }
    out->push_back(c);
    if (out->size() > 1 << 16) return false;  // header bomb guard
  }
}

bool WriteAll(int fd, const std::string& data) {
  size_t sent = 0;
  while (sent < data.size()) {
    ssize_t w = write(fd, data.data() + sent, data.size() - sent);
    if (w <= 0) return false;
    sent += static_cast<size_t>(w);
  }
  return true;
}

std::string Bulk(const std::string& s) {
  return "$" + std::to_string(s.size()) + "\r\n" + s + "\r\n";
}
const std::string kNil = "$-1\r\n";
const std::string kNilArray = "*-1\r\n";
std::string Int(long long v) { return ":" + std::to_string(v) + "\r\n"; }
std::string Err(const std::string& m) { return "-ERR " + m + "\r\n"; }

// ---- command dispatch ------------------------------------------------------
std::string Execute(std::vector<std::string>& args) {
  std::string cmd = args[0];
  for (auto& c : cmd) c = static_cast<char>(toupper(c));
  MaybePurgeExpired();

  if (cmd == "PING") return "+PONG\r\n";
  if (cmd == "SHUTDOWN") {
    g_shutdown.store(true);
    if (g_listen_fd >= 0) shutdown(g_listen_fd, SHUT_RDWR);
    return "+OK\r\n";
  }
  if (cmd == "FLUSHALL") {
    std::lock_guard<std::mutex> l(g_store.mu);
    g_store.kv.clear();
    g_store.lists.clear();
    g_store.ttl.clear();
    return "+OK\r\n";
  }
  if (cmd == "TTL" && args.size() == 2) {
    // redis semantics: -2 missing key, -1 no expiry, else seconds left
    // (rounded UP, like redis). A key DEL'd while its TTL survives
    // (the kvd reply-queue deviation) reports -2 here — the armed TTL
    // is an internal condemnation, not key liveness.
    std::lock_guard<std::mutex> l(g_store.mu);
    bool exists = g_store.kv.count(args[1]) || g_store.lists.count(args[1]);
    if (!exists) return Int(-2);
    auto it = g_store.ttl.find(args[1]);
    if (it == g_store.ttl.end()) return Int(-1);
    auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                  it->second - std::chrono::steady_clock::now())
                  .count();
    return Int(ms <= 0 ? 0 : (ms + 999) / 1000);
  }
  if (cmd == "EXPIRE" && args.size() == 3) {
    double secs = strtod(args[2].c_str(), nullptr);
    std::lock_guard<std::mutex> l(g_store.mu);
    // unlike Redis, the key need not exist yet: the predictor arms the
    // TTL when it ISSUES a query, so even a reply that arrives after
    // the gather's discard is already condemned
    g_store.ttl[args[1]] =
        std::chrono::steady_clock::now() +
        std::chrono::duration_cast<std::chrono::steady_clock::duration>(
            std::chrono::duration<double>(secs));
    return Int(1);
  }
  if (cmd == "SET" && args.size() == 3) {
    std::lock_guard<std::mutex> l(g_store.mu);
    g_store.kv[args[1]] = std::move(args[2]);
    return "+OK\r\n";
  }
  if (cmd == "GET" && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    auto it = g_store.kv.find(args[1]);
    return it == g_store.kv.end() ? kNil : Bulk(it->second);
  }
  if (cmd == "DEL" && args.size() >= 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    long long n = 0;
    for (size_t i = 1; i < args.size(); ++i) {
      n += g_store.kv.erase(args[i]);
      n += g_store.lists.erase(args[i]);
    }
    return Int(n);
  }
  if (cmd == "EXISTS" && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    return Int(g_store.kv.count(args[1]) || g_store.lists.count(args[1]));
  }
  if (cmd == "KEYS" && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    std::string out;
    long long n = 0;
    for (auto& [k, _] : g_store.kv)
      if (GlobMatch(args[1].c_str(), k.c_str())) { out += Bulk(k); ++n; }
    for (auto& [k, _] : g_store.lists)
      if (GlobMatch(args[1].c_str(), k.c_str())) { out += Bulk(k); ++n; }
    return "*" + std::to_string(n) + "\r\n" + out;
  }
  if (cmd == "INCR" && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    auto& v = g_store.kv[args[1]];
    long long n = v.empty() ? 0 : strtoll(v.c_str(), nullptr, 10);
    v = std::to_string(n + 1);
    return Int(n + 1);
  }
  if ((cmd == "LPUSH" || cmd == "RPUSH") && args.size() >= 3) {
    std::lock_guard<std::mutex> l(g_store.mu);
    auto& dq = g_store.lists[args[1]];
    for (size_t i = 2; i < args.size(); ++i) {
      if (cmd == "LPUSH") dq.push_front(std::move(args[i]));
      else dq.push_back(std::move(args[i]));
    }
    g_store.list_cv.notify_all();
    return Int(static_cast<long long>(dq.size()));
  }
  if ((cmd == "LPOP" || cmd == "RPOP") && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    auto it = g_store.lists.find(args[1]);
    if (it == g_store.lists.end() || it->second.empty()) return kNil;
    std::string v;
    if (cmd == "LPOP") {
      v = std::move(it->second.front());
      it->second.pop_front();
    } else {
      v = std::move(it->second.back());
      it->second.pop_back();
    }
    return Bulk(v);
  }
  if (cmd == "LLEN" && args.size() == 2) {
    std::lock_guard<std::mutex> l(g_store.mu);
    auto it = g_store.lists.find(args[1]);
    return Int(it == g_store.lists.end()
                   ? 0
                   : static_cast<long long>(it->second.size()));
  }
  if (cmd == "BRPOP" && args.size() >= 3) {
    // BRPOP key [key...] timeout_seconds — pops the *tail* of the first
    // non-empty key; replies *2 [key, value] or nil-array on timeout.
    double timeout_s = strtod(args.back().c_str(), nullptr);
    std::vector<std::string> keys(args.begin() + 1, args.end() - 1);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                        std::chrono::duration<double>(timeout_s));
    std::unique_lock<std::mutex> l(g_store.mu);
    while (true) {
      for (auto& k : keys) {
        auto it = g_store.lists.find(k);
        if (it != g_store.lists.end() && !it->second.empty()) {
          std::string v = std::move(it->second.back());
          it->second.pop_back();
          return "*2\r\n" + Bulk(k) + Bulk(v);
        }
      }
      if (g_shutdown.load()) return kNilArray;
      if (timeout_s <= 0) {  // 0 = wait forever (redis semantics)
        g_store.list_cv.wait_for(l, std::chrono::milliseconds(100));
      } else {
        if (g_store.list_cv.wait_until(l, deadline) ==
            std::cv_status::timeout) {
          // re-check once after timeout, then give up
          for (auto& k : keys) {
            auto it = g_store.lists.find(k);
            if (it != g_store.lists.end() && !it->second.empty()) {
              std::string v = std::move(it->second.back());
              it->second.pop_back();
              return "*2\r\n" + Bulk(k) + Bulk(v);
            }
          }
          return kNilArray;
        }
      }
    }
  }
  return Err("unknown command or wrong arity: " + cmd);
}

void ServeConn(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::string line;
  while (!g_shutdown.load()) {
    if (!ReadLine(fd, &line) || line.empty() || line[0] != '*') break;
    long n = strtol(line.c_str() + 1, nullptr, 10);
    if (n <= 0 || n > 1 << 20) break;
    std::vector<std::string> args;
    args.reserve(static_cast<size_t>(n));
    bool ok = true;
    for (long i = 0; i < n && ok; ++i) {
      if (!ReadLine(fd, &line) || line.empty() || line[0] != '$') {
        ok = false;
        break;
      }
      long len = strtol(line.c_str() + 1, nullptr, 10);
      if (len < 0 || len > (1L << 31)) { ok = false; break; }
      std::string payload(static_cast<size_t>(len), '\0');
      if (!ReadN(fd, payload.data(), static_cast<size_t>(len))) {
        ok = false;
        break;
      }
      char crlf[2];
      if (!ReadN(fd, crlf, 2)) { ok = false; break; }
      args.push_back(std::move(payload));
    }
    if (!ok || args.empty()) break;
    if (!WriteAll(fd, Execute(args))) break;
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = 6399;
  const char* host = "127.0.0.1";
  for (int i = 1; i < argc - 1; ++i) {
    if (!strcmp(argv[i], "--port")) port = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--host")) host = argv[i + 1];
  }
  signal(SIGPIPE, SIG_IGN);

  g_listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(g_listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (bind(g_listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
      0) {
    perror("bind");
    return 1;
  }
  // port 0 → kernel-assigned; report the real one for the spawner
  socklen_t alen = sizeof(addr);
  getsockname(g_listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  if (listen(g_listen_fd, 128) < 0) {
    perror("listen");
    return 1;
  }
  fprintf(stdout, "rafiki-kvd listening on %s:%d\n", host,
          ntohs(addr.sin_port));
  fflush(stdout);

  std::vector<std::thread> conns;
  while (!g_shutdown.load()) {
    int fd = accept(g_listen_fd, nullptr, nullptr);
    if (fd < 0) break;
    conns.emplace_back(ServeConn, fd);
  }
  g_store.list_cv.notify_all();
  close(g_listen_fd);
  for (auto& t : conns)
    if (t.joinable()) t.join();
  return 0;
}
