"""Hot-path ops: Pallas TPU kernels + sequence-parallel ring attention.

On TPU the flash-attention / patch-embed kernels lower through Mosaic;
off-TPU the default dispatch routes to equivalent pure-XLA math (the
Pallas interpreter is test-only — see ``ops/common.py``). Ring attention
shards the sequence axis over a mesh and rotates K/V via ppermute
(long-context support; ``ops/ring_attention.py``); Ulysses swaps the
sharded axis head↔sequence with two all-to-alls and runs the ordinary
kernel per head group (``ops/ulysses.py``); the MoE feed-forward routes
tokens to experts sharded over the mesh (``ops/moe.py``).
"""

from .attention import flash_attention, flash_attention_lse, mha
from .moe import MoEFeedForward, moe_aux_loss
from .patch_embed import extract_patches, matmul_bias, patch_embed
from .ring_attention import ring_attention
from .ulysses import ulysses_attention

__all__ = ["flash_attention", "flash_attention_lse", "mha",
           "MoEFeedForward", "moe_aux_loss",
           "patch_embed", "matmul_bias",
           "extract_patches", "ring_attention", "ulysses_attention"]
