"""Pallas TPU kernels for the hot ops (XLA-fallback-free on TPU;
interpreter mode on CPU so tests run the same code path)."""

from .attention import flash_attention, mha
from .patch_embed import extract_patches, matmul_bias, patch_embed

__all__ = ["flash_attention", "mha", "patch_embed", "matmul_bias",
           "extract_patches"]
