"""Shared dispatch policy for the Pallas ops.

One place decides when the kernels run vs the pure-XLA fallback so
attention and patch-embed can't drift apart.
"""

from __future__ import annotations

from typing import Optional

import jax


def use_xla_fallback(interpret: Optional[bool]) -> bool:
    """True → run the mathematically equivalent pure-XLA path.

    Policy: templates call ops with ``interpret=None``; off-TPU that means
    the XLA path (the Pallas interpreter is orders of magnitude slower on
    CPU and is exercised separately by the kernel-equivalence tests via
    ``interpret=True``). On TPU, ``None`` means real Mosaic lowering.
    """
    return interpret is None and jax.default_backend() != "tpu"
