"""Shared dispatch policy for the Pallas ops.

One place decides when the kernels run vs the pure-XLA fallback so
attention and patch-embed can't drift apart.
"""

from __future__ import annotations

from typing import Optional

import jax


def use_xla_fallback(interpret: Optional[bool]) -> bool:
    """True → run the mathematically equivalent pure-XLA path.

    Policy: templates call ops with ``interpret=None``; off-TPU that means
    the XLA path (the Pallas interpreter is orders of magnitude slower on
    CPU and is exercised separately by the kernel-equivalence tests via
    ``interpret=True``). On TPU, ``None`` means real Mosaic lowering.
    """
    return interpret is None and jax.default_backend() != "tpu"


def shard_map_checked(f, mesh, in_specs, out_specs):
    """``shard_map`` with the replication/varying checker ON — for pure
    XLA bodies (no ``pallas_call``). Besides the safety net, the checker
    is load-bearing on older jax: transposing a ``psum`` (grad through a
    replicated ``P()`` output) mis-specs under ``check_rep=False``."""
    try:
        smap = jax.shard_map
    except AttributeError:  # pre-promotion jax: experimental namespace
        from jax.experimental.shard_map import shard_map as smap
    return smap(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def shard_map_kernels(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` configured for bodies that may issue Pallas
    calls. The varying-manual-axes checker cannot type a ``pallas_call``'s
    outputs (jax requires an explicit ``vma`` on every out ShapeDtypeStruct
    it cannot infer), so kernel-bearing maps disable it; correctness of
    the replication/varying structure is covered by the oracle-equivalence
    tests instead. Falls back to the pre-vma ``check_rep`` keyword on
    older jax."""
    try:
        smap = jax.shard_map
    except AttributeError:  # pre-promotion jax: experimental namespace
        from jax.experimental.shard_map import shard_map as smap
    try:
        return smap(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, check_vma=False)
    except TypeError:  # older jax spells it check_rep
        return smap(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, check_rep=False)


def gqa_repeat_factor(n_heads: int, n_kv_heads: int) -> int:
    """Validate the GQA head pairing (q head i ↔ kv head ``i // rep``,
    the ``jnp.repeat`` convention shared by the sequence-parallel
    attention ops) and return ``rep = n_heads / n_kv_heads``."""
    if n_heads % n_kv_heads:
        raise ValueError(f"q heads {n_heads} must be a multiple of kv "
                         f"heads {n_kv_heads}")
    return n_heads // n_kv_heads
