"""Paged-native flash decode attention as a Pallas TPU kernel.

The paged KV pool (serving PR 5) cut cache HBM 2.56x but the decode hot
loop paid the win back: every step ``_DecoderAttention`` gathered all of
a slot's pages back into logical ``(b, max_len, heads, dh)`` order
before the masked softmax, re-materializing the whole logical KV per
generated token. This kernel consumes the pool **directly**:

- **Grid over (batch, kv-head tile, pages).** Each program reads ONE
  ``(page_size, block_h, dh)`` K/V block straight out of the pool — the
  block table rides in as a scalar-prefetch operand and the BlockSpec
  index map does the table walk (``tabs[b, page]``), so the page gather
  never materializes in HBM.
- **LSE-merged partial softmax.** Per page the program computes a
  partial (max, sum, weighted-V accumulator) and folds it into running
  f32 state in VMEM scratch — the same online-softmax recurrence
  ``_attn_fwd_kernel`` streams key blocks with, here streamed across
  grid steps (TPU grids execute sequentially per core; the page axis is
  minor, so a (batch, head-tile) row sees its pages back to back and
  the final page step writes the normalized output).
- **Live pages only.** A slot at position ``t`` owns ``t // page_size
  + 1`` live pages; later grid steps map their block index to pool
  page 0 (the engine's scratch page — dead table entries already point
  there) and skip compute via ``pl.when``. Consecutive same-index
  fetches are elided by the pipeline, so per-step HBM traffic scales
  with LIVE tokens, not ``max_len``.
- **Fused int8-KV dequant.** Quantized pools pass their f32 absmax
  scale rows (same pool geometry, same table walk); the kernel
  dequantizes each page block in registers — the scale multiply fuses
  into the f32 attention math and no dequantized cache ever exists.
- **GQA without the repeat.** Queries arrive grouped per kv head
  (``rep = n_heads / n_kv_heads`` query rows share one K/V page
  block), so the ``jnp.repeat`` the gather path pays per step never
  happens. ``block_h`` tiles kv heads per program exactly like
  ``flash_attention``'s head tiling (env default via
  ``_env_block_h``, same divisibility fallback).

The single-token step above was PR 10; ``paged_window_attention``
generalizes it to an (s >= 1) **query window** so chunked prefill and
speculative-verify calls run paged-native too. The grid gains a
query-tile dimension (``block_q`` window rows per program), the same
block-table walk and LSE-merge recurrence stream across pages per query
tile, and the causal mask becomes per ROW: window token i at absolute
position ``positions[b, i]`` sees keys ``k_pos <= positions[b, i]``
(the s==1 "last token sees everything" rule is the degenerate case).
Window positions must be NONDECREASING along each row — exactly what
the engine's prefill/verify windows provide (idle and overhang rows
repeat the last real entry) — so a query tile's last row bounds its
live pages and dead-page skipping carries over per tile.

Dispatch policy (mirrors ``ops/attention.py``): the decode path runs
the kernel on TPU by default and falls back to the page gather off-TPU
(``resolve_paged_kernel``); multi-token windows additionally honor the
``RAFIKI_PAGED_KERNEL_WINDOWS`` escape hatch
(``resolve_paged_window_kernel``), which drops the engine back to
step-only kernel mode without touching the s==1 hot loop.
``interpret=True`` forces the kernel through the Pallas interpreter,
which is how the CPU tier-1 equivalence tests run it. Numerics: f32
accumulation regardless of pool dtype; the online softmax is the
associativity-reordered twin of the gather path's masked softmax, so
outputs agree to f32 roundoff (token-exact in practice — proven per
decode mode in ``tests/test_paged_kv.py``).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

from rafiki_tpu.ops.attention import NEG_INF, _env_block_h, \
    _resolve_interpret
from rafiki_tpu.ops.common import gqa_repeat_factor


def resolve_paged_kernel(flag: Optional[bool]) -> bool:
    """The serving dispatch rule for the ``paged_kernel`` flag:
    ``None`` (auto, the fleet default) runs the kernel only on a real
    TPU backend — off-TPU the page gather through XLA is orders of
    magnitude faster than the Pallas interpreter. An explicit
    ``True``/``False`` wins either way (tests force ``True`` and ride
    the interpreter via ``_resolve_interpret``)."""
    if flag is None:
        return jax.default_backend() == "tpu"
    return bool(flag)


def resolve_paged_window_kernel(flag: Optional[bool]) -> bool:
    """Dispatch rule for the MULTI-TOKEN window legs (chunked prefill,
    speculative verify). Windows ride the same tri-state ``paged_kernel``
    flag as the s==1 step, with one extra operational escape hatch:
    ``RAFIKI_PAGED_KERNEL_WINDOWS=0`` (or ``false``/``off``) forces the
    window legs back onto the gather fallback — step-only kernel mode —
    without touching the single-token hot loop. Default is enabled, so
    wherever ``resolve_paged_kernel`` says kernel, windows go kernel
    too."""
    if os.environ.get("RAFIKI_PAGED_KERNEL_WINDOWS", "1").lower() in (
            "0", "false", "off"):
        return False
    return resolve_paged_kernel(flag)


def _partitioner_shield(call, *operands):
    """Run a pallas call as a fully-replicated ``shard_map`` manual
    region when the Pallas INTERPRETER executes under a multi-device
    backend (the CPU tier-1 test mesh).

    Interpret mode lowers the kernel to an ordinary XLA while-loop, and
    the auto-SPMD partitioner is free to slice its internals across
    devices. Empirically that choice leaks OUT of the kernel: with the
    loop in the program, the partitioner re-shards the surrounding
    cache-update scatter into an add-combined form that applies every
    update once PER REPLICA GROUP — the KV pool comes back exactly
    doubled (reproduced under the 8-device CPU mesh; the gather-only
    twin of the same program is correct). Marking the kernel region
    manual with every operand replicated keeps the partitioner out of
    the interpreter loop entirely, and the surrounding program then
    partitions exactly as the gather path does. Real-TPU programs
    (``interpret=False``) never take this wrapper: there the kernel is
    an opaque custom call and partitions as it always has.
    """
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.asarray(jax.devices()), ("_pk_replica",))
    spec = PartitionSpec()
    # materialize TRUE replicas first: an operand may reach this point
    # as a pending partial-sum (the partitioner splitting an upstream
    # contraction), and ``check_rep=False`` would hand each device its
    # partial as if it were the whole value. The explicit constraint
    # forces the all-reduce BEFORE the manual region.
    replicated = NamedSharding(mesh, spec)
    operands = tuple(
        jax.lax.with_sharding_constraint(o, replicated)
        for o in operands)
    return shard_map(
        call, mesh=mesh, in_specs=(spec,) * len(operands),
        out_specs=spec, check_rep=False)(*operands)


def kv_cache_write(cache, idx0, idx1, values,
                   interpret: Optional[bool] = None):
    """Scatter a decode window's K/V (or scale) rows into the KV cache:
    ``cache[idx0[b, i], idx1[b, i]] = values[b, i]`` — ``(pool page,
    page slot)`` indices for the paged layout, ``(batch row, position)``
    for the contiguous one.

    Semantically this is nothing but ``cache.at[idx0, idx1].set(values)``
    — and that is exactly what runs on real TPU and on a single-device
    CPU. Under a MULTI-device interpret mesh it detours through the
    partitioner shield instead, because the auto-SPMD partitioner
    re-lowers the inline set-scatter in a way that lets the cache
    replicas diverge and then reconciles them ADDITIVELY: the rope'd K
    projection reaches the scatter as a pending partial-sum, each
    replica group writes its partial, and the stored K comes back
    exactly DOUBLED (reproduced on the 8-device CPU test mesh against
    a single-device ground truth; V, whose updates happen to reach the
    scatter fully reduced, survives). The corruption was invisible
    while every decode program shared it — token parity held between
    equally-wrong twins — and surfaced the moment one path stopped
    being wrong. Routing the write through the replicated manual
    region (see :func:`_partitioner_shield`) pins the single-device
    lowering everywhere the interpreter runs.
    """
    def write(c, i0, i1, v):
        return c.at[i0, i1].set(v)

    if _resolve_interpret(interpret) and jax.device_count() > 1:
        return _partitioner_shield(write, cache, idx0, idx1, values)
    return write(cache, idx0, idx1, values)


def _paged_decode_kernel(t_ref, tab_ref, q_ref, k_ref, v_ref, *rest,
                         sm_scale: float, page_size: int, block_h: int,
                         n_tables: int, quantized: bool):
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest
    bi = pl.program_id(0)
    pg = pl.program_id(2)
    t = t_ref[bi]  # this slot's query position (keys k_pos <= t live)
    n_live = t // page_size + 1

    @pl.when(pg == 0)
    def _init():  # fresh (batch, head-tile) row: reset the running state
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(pg < n_live)
    def _partial():  # dead pages: no compute (their fetch was elided by
        # the index map collapsing them onto the scratch page)
        k_pos = pg * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        mask = k_pos <= t  # (1, page_size): masks the last live page's
        # dead tail AND any speculative-overwrite rows above t
        for hh in range(block_h):  # static unroll over the head tile
            q = q_ref[0, hh].astype(jnp.float32) * sm_scale  # (rep, dh)
            k = k_ref[0, :, hh, :].astype(jnp.float32)  # (page_size, dh)
            v = v_ref[0, :, hh, :].astype(jnp.float32)
            if quantized:  # dequant in registers, fused into the math
                k = k * ks_ref[0, :, hh][:, None]
                v = v * vs_ref[0, :, hh][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (rep, page_size)
            s = jnp.where(mask, s, NEG_INF)

            m_prev = m_scr[hh]  # (rep, 1) running max
            m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_scr[hh] = l_scr[hh] * alpha + jnp.sum(p, -1, keepdims=True)
            acc_scr[hh] = acc_scr[hh] * alpha + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (rep, dh)
            m_scr[hh] = m_new

    @pl.when(pg == n_tables - 1)
    def _finish():  # position 0 is always live, so l > 0 on every row
        o_ref[0] = (acc_scr[...] /
                    jnp.maximum(l_scr[...], 1e-30)).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, page_tables, positions,
                           sm_scale: float,
                           k_scale=None, v_scale=None,
                           block_h: Optional[int] = None,
                           interpret: Optional[bool] = None
                           ) -> jnp.ndarray:
    """Single-token decode attention straight off a paged KV pool.

    - ``q``: (b, n_heads, dh) — this step's query vector per slot.
    - ``k_pool``/``v_pool``: (n_pages, page_size, n_kv_heads, dh), the
      per-layer pool (f32/bf16, or int8 with ``k_scale``/``v_scale``
      absmax rows of shape (n_pages, page_size, n_kv_heads)).
    - ``page_tables``: (b, n_tables) int32 logical→pool page map. Dead
      entries (at or past a slot's live count) must point at a valid
      pool page — the serving engine keeps them at 0, the scratch page.
      The table may be narrower than ``max_len/page_size``: it only has
      to cover every slot's live pages (the engine passes its
      live-width slice).
    - ``positions``: (b,) int32 query positions; keys ``k_pos <=
      positions[i]`` are visible to slot i (the decode-branch mask).

    Returns (b, n_heads, dh) in ``q``'s dtype. GQA queries are grouped
    per kv head internally (``jnp.repeat`` convention: q head h ↔ kv
    head ``h // rep``). ``block_h`` tiles kv heads per program
    (default: the ``RAFIKI_ATTN_BLOCK_H`` fleet default through the
    same divisibility fallback as ``flash_attention``).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n_heads, dh = q.shape
    n_pages, page_size, n_kv, dh_k = k_pool.shape
    if dh_k != dh:
        raise ValueError(f"head_dim mismatch: q has {dh}, pool {dh_k}")
    rep = gqa_repeat_factor(n_heads, n_kv)
    n_tables = page_tables.shape[1]
    if block_h is None:
        block_h = _env_block_h(n_kv)
    if block_h < 1 or n_kv % block_h:
        raise ValueError(f"block_h={block_h} must be >= 1 and divide "
                         f"the kv head count ({n_kv})")
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be passed together")
    interpret = _resolve_interpret(interpret)

    qh = q.reshape(b, n_kv, rep, dh)
    t = jnp.asarray(positions, jnp.int32)
    tabs = jnp.asarray(page_tables, jnp.int32)

    def q_map(bi, kh, pg, t_ref, tab_ref):
        return (bi, kh, 0, 0)

    def kv_map(bi, kh, pg, t_ref, tab_ref):
        # the block-table walk: live pages come from the table, dead
        # ones collapse onto pool page 0 so consecutive dead steps
        # re-use one (skipped-compute) fetch instead of streaming
        # garbage — per-step traffic scales with live tokens
        live = pg <= t_ref[bi] // page_size
        return (jnp.where(live, tab_ref[bi, pg], 0), 0, kh, 0)

    def sc_map(bi, kh, pg, t_ref, tab_ref):
        live = pg <= t_ref[bi] // page_size
        return (jnp.where(live, tab_ref[bi, pg], 0), 0, kh)

    in_specs = [
        pl.BlockSpec((1, block_h, rep, dh), q_map),
        pl.BlockSpec((1, page_size, block_h, dh), kv_map),
        pl.BlockSpec((1, page_size, block_h, dh), kv_map),
    ]
    operands = [qh, k_pool, v_pool]
    if quantized:
        in_specs += [pl.BlockSpec((1, page_size, block_h), sc_map),
                     pl.BlockSpec((1, page_size, block_h), sc_map)]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_kv // block_h, n_tables),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_h, rep, dh), q_map),
        scratch_shapes=[
            pltpu.VMEM((block_h, rep, 1), jnp.float32),   # running max
            pltpu.VMEM((block_h, rep, 1), jnp.float32),   # running sum
            pltpu.VMEM((block_h, rep, dh), jnp.float32),  # weighted V
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel, sm_scale=float(sm_scale),
        page_size=page_size, block_h=block_h, n_tables=n_tables,
        quantized=quantized)
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, rep, dh), q.dtype),
        interpret=interpret,
    )
    if interpret and jax.device_count() > 1:
        out = _partitioner_shield(call, t, tabs, *operands)
    else:
        out = call(t, tabs, *operands)
    return out.reshape(b, n_heads, dh)


def _paged_window_kernel(t_ref, tab_ref, q_ref, k_ref, v_ref,
                         *rest, sm_scale: float, page_size: int,
                         block_h: int, block_q: int, rep: int,
                         n_tables: int, quantized: bool):
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest
    bi = pl.program_id(0)
    qt = pl.program_id(2)
    pg = pl.program_id(3)
    # this query tile's absolute positions, straight off the scalar
    # prefetch (SMEM) — the same array the index maps walk, so masks
    # and fetches can never disagree
    tile_t = t_ref[bi, pl.ds(qt * block_q, block_q)]  # (block_q,)
    # positions are NONDECREASING along the window (the engine repeats
    # the last real entry into idle/overhang rows), so this tile's last
    # row bounds its live pages — the per-tile twin of the step
    # kernel's n_live
    n_live = tile_t[block_q - 1] // page_size + 1

    @pl.when(pg == 0)
    def _init():  # fresh (batch, head-tile, query-tile) row
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(pg < n_live)
    def _partial():  # dead pages: no compute, fetch collapsed onto the
        # scratch page by the index map
        k_pos = pg * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)  # (1, page_size)
        # per-ROW causal horizon: query row r is window token r // rep
        # and sees keys k_pos <= its own absolute position — inside the
        # window, earlier tokens do NOT see later tokens' keys
        t_rows = jnp.repeat(tile_t, rep)[:, None]  # (bq*rep, 1)
        mask = k_pos <= t_rows  # (block_q*rep, page_size)
        for hh in range(block_h):  # static unroll over the head tile
            q = (q_ref[0, hh].reshape(block_q * rep, -1)
                 .astype(jnp.float32) * sm_scale)  # (bq*rep, dh)
            k = k_ref[0, :, hh, :].astype(jnp.float32)  # (page_size, dh)
            v = v_ref[0, :, hh, :].astype(jnp.float32)
            if quantized:  # dequant in registers, fused into the math
                k = k * ks_ref[0, :, hh][:, None]
                v = v * vs_ref[0, :, hh][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (bq*rep, psz)
            s = jnp.where(mask, s, NEG_INF)

            m_prev = m_scr[hh]  # (bq*rep, 1) running max
            m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_scr[hh] = l_scr[hh] * alpha + jnp.sum(p, -1, keepdims=True)
            acc_scr[hh] = acc_scr[hh] * alpha + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (bq*rep, dh)
            m_scr[hh] = m_new

    @pl.when(pg == n_tables - 1)
    def _finish():  # k_pos 0 <= any position, so l > 0 on every row
        o_ref[0] = (acc_scr[...] /
                    jnp.maximum(l_scr[...], 1e-30)).reshape(
                        block_h, block_q, rep, -1).astype(o_ref.dtype)


def _default_block_q(s: int) -> int:
    """Largest window-tile width <= 16 that divides the window — the
    same divisibility-fallback spirit as ``_env_block_h``."""
    for d in range(min(s, 16), 0, -1):
        if s % d == 0:
            return d
    return 1


def paged_window_attention(q, k_pool, v_pool, page_tables, positions,
                           sm_scale: float,
                           k_scale=None, v_scale=None,
                           block_h: Optional[int] = None,
                           block_q: Optional[int] = None,
                           interpret: Optional[bool] = None
                           ) -> jnp.ndarray:
    """Multi-token window attention straight off a paged KV pool.

    The (s >= 1) generalization of ``paged_decode_attention`` serving
    chunked prefill and speculative-verify windows:

    - ``q``: (b, s, n_heads, dh) — a window of s query vectors per slot.
    - ``k_pool``/``v_pool``/``k_scale``/``v_scale``: exactly as in
      ``paged_decode_attention`` (the window's own K/V rows are already
      written into the pool before the call — the decode branch writes
      the chunk first, then attends).
    - ``page_tables``: (b, n_tables) int32, dead entries on the scratch
      page, live-width slices welcome — identical contract to the step
      kernel.
    - ``positions``: (b, s) int32, the absolute position of every window
      token; row i of the window sees keys ``k_pos <= positions[b, i]``
      (causal INSIDE the window, not just at its end). Rows must be
      NONDECREASING: the engine's windows guarantee this (prefill pads
      overhang with the last entry, verify freezes inactive slots), and
      the kernel exploits it to bound live pages per query tile.

    Returns (b, s, n_heads, dh) in ``q``'s dtype. ``block_q`` tiles the
    window (must divide s; default: largest divisor <= 16), ``block_h``
    tiles kv heads as in the step kernel. With s == 1 this computes
    bit-for-bit the same output as ``paged_decode_attention`` — same op
    shapes, same order — which the property tests pin.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, n_heads, dh = q.shape
    n_pages, page_size, n_kv, dh_k = k_pool.shape
    if dh_k != dh:
        raise ValueError(f"head_dim mismatch: q has {dh}, pool {dh_k}")
    rep = gqa_repeat_factor(n_heads, n_kv)
    n_tables = page_tables.shape[1]
    if block_h is None:
        block_h = _env_block_h(n_kv)
    if block_h < 1 or n_kv % block_h:
        raise ValueError(f"block_h={block_h} must be >= 1 and divide "
                         f"the kv head count ({n_kv})")
    if block_q is None:
        block_q = _default_block_q(s)
    if block_q < 1 or s % block_q:
        raise ValueError(f"block_q={block_q} must be >= 1 and divide "
                         f"the window length ({s})")
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be passed together")
    interpret = _resolve_interpret(interpret)

    t = jnp.asarray(positions, jnp.int32)
    if t.shape != (b, s):
        raise ValueError(f"positions must be (b, s)=({b}, {s}), got "
                         f"{t.shape}")
    # group GQA query rows per kv head, window-major inside the head
    # tile: (b, n_kv, s, rep, dh) — rep rows of one token stay adjacent
    qw = q.reshape(b, s, n_kv, rep, dh).transpose(0, 2, 1, 3, 4)
    tabs = jnp.asarray(page_tables, jnp.int32)

    def q_map(bi, kh, qt, pg, t_ref, tab_ref):
        return (bi, kh, qt, 0, 0)

    def kv_map(bi, kh, qt, pg, t_ref, tab_ref):
        # the block-table walk, bounded per QUERY TILE: nondecreasing
        # positions make the tile's last row its page horizon, so dead
        # pages collapse onto the scratch page exactly as in the step
        # kernel
        live = pg <= t_ref[bi, qt * block_q + block_q - 1] // page_size
        return (jnp.where(live, tab_ref[bi, pg], 0), 0, kh, 0)

    def sc_map(bi, kh, qt, pg, t_ref, tab_ref):
        live = pg <= t_ref[bi, qt * block_q + block_q - 1] // page_size
        return (jnp.where(live, tab_ref[bi, pg], 0), 0, kh)

    in_specs = [
        pl.BlockSpec((1, block_h, block_q, rep, dh), q_map),
        pl.BlockSpec((1, page_size, block_h, dh), kv_map),
        pl.BlockSpec((1, page_size, block_h, dh), kv_map),
    ]
    operands = [qw, k_pool, v_pool]
    if quantized:
        in_specs += [pl.BlockSpec((1, page_size, block_h), sc_map),
                     pl.BlockSpec((1, page_size, block_h), sc_map)]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_kv // block_h, s // block_q, n_tables),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_h, block_q, rep, dh), q_map),
        scratch_shapes=[
            pltpu.VMEM((block_h, block_q * rep, 1), jnp.float32),
            pltpu.VMEM((block_h, block_q * rep, 1), jnp.float32),
            pltpu.VMEM((block_h, block_q * rep, dh), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_window_kernel, sm_scale=float(sm_scale),
        page_size=page_size, block_h=block_h, block_q=block_q, rep=rep,
        n_tables=n_tables, quantized=quantized)
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, s, rep, dh), q.dtype),
        interpret=interpret,
    )
    if interpret and jax.device_count() > 1:
        out = _partitioner_shield(call, t, tabs, *operands)
    else:
        out = call(t, tabs, *operands)
    return out.transpose(0, 2, 1, 3, 4).reshape(b, s, n_heads, dh)


def _paged_attention_reference(q, k_pool, v_pool, page_tables, positions,
                               sm_scale: float, k_scale=None,
                               v_scale=None) -> jnp.ndarray:
    """Pure-XLA oracle: gather the pages back into logical order (the
    pre-kernel serving path) and run the masked softmax in f32. The
    kernel-equivalence property tests compare against this."""
    b, n_heads, dh = q.shape
    _, page_size, n_kv, _ = k_pool.shape
    rep = gqa_repeat_factor(n_heads, n_kv)
    n_tables = page_tables.shape[1]
    length = n_tables * page_size

    def rows(pool):  # (b, length, n_kv, ...) logical view
        return pool[page_tables].reshape((b, length) + pool.shape[2:])

    k = rows(k_pool).astype(jnp.float32)
    v = rows(v_pool).astype(jnp.float32)
    if k_scale is not None:
        k = k * rows(k_scale)[..., None]
        v = v * rows(v_scale)[..., None]
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                   k) * sm_scale
    k_pos = jnp.arange(length)[None, None, :]
    s = jnp.where(k_pos <= jnp.asarray(positions)[:, None, None],
                  s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", p, v).astype(q.dtype)


def _paged_window_reference(q, k_pool, v_pool, page_tables, positions,
                            sm_scale: float, k_scale=None,
                            v_scale=None) -> jnp.ndarray:
    """Pure-XLA window oracle: gather the pages back into logical order
    and run the per-row masked softmax in f32 — the same math the
    multi-token gather fallback in ``_DecoderAttention`` computes."""
    b, s, n_heads, dh = q.shape
    _, page_size, n_kv, _ = k_pool.shape
    rep = gqa_repeat_factor(n_heads, n_kv)
    n_tables = page_tables.shape[1]
    length = n_tables * page_size

    def rows(pool):  # (b, length, n_kv, ...) logical view
        return pool[page_tables].reshape((b, length) + pool.shape[2:])

    k = rows(k_pool).astype(jnp.float32)
    v = rows(v_pool).astype(jnp.float32)
    if k_scale is not None:
        k = k * rows(k_scale)[..., None]
        v = v * rows(v_scale)[..., None]
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k) * sm_scale
    k_pos = jnp.arange(length)[None, None, None, :]
    t = jnp.asarray(positions)[:, None, :, None]  # (b, 1, s, 1)
    scores = jnp.where(k_pos <= t, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v).astype(q.dtype)
