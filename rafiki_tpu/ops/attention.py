"""Fused multi-head attention (flash-style) as a Pallas TPU kernel.

Replaces the cuDNN fused attention the reference's templates get for free
inside TF/PyTorch (SURVEY.md §2.1: the rebuild's native obligation is
XLA/Pallas kernels; ViT attention is the named target). Design:

- Online-softmax streaming over key blocks (never materializes the S×S
  score matrix in HBM): for each query block the kernel keeps running
  (max, sum, weighted-V accumulator) in f32 and rescales as new key blocks
  arrive — the flash-attention recurrence.
- Block sizes default to 128 to match MXU tiling; inputs are padded to
  block multiples by the wrapper and the pad keys are masked out, so any
  sequence length works.
- f32 accumulation regardless of input dtype (bf16 in, bf16 out, f32 math).
- Backward pass: recompute-based custom VJP in XLA (correctness first; the
  fwd kernel is the serving hot path). CPU backend runs the same kernel in
  interpreter mode, so tests exercise the identical code path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _attn_fwd_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, *, sm_scale: float,
                     causal: bool, block_q: int, block_k: int,
                     n_kv_blocks: int):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (block_q, d)
    kv_len = len_ref[0]  # this example's valid key count (pads masked out)

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (block_q, block_k)

        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    # skip key blocks that are fully masked: past this example's kv_len,
    # and (causal) strictly after this query block
    n_blocks = jnp.minimum(
        jnp.asarray(n_kv_blocks, jnp.int32),
        (kv_len + block_k - 1) // block_k)
    if causal:
        n_blocks = jnp.minimum(
            n_blocks, (qb * block_q + block_q + block_k - 1) // block_k)
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_attention_fwd_impl(q, k, v, kv_lens, sm_scale: float,
                              causal: bool, block_q: int, block_k: int,
                              interpret: Optional[bool]):
    from jax.experimental import pallas as pl

    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qp = _pad_to(q, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    sq_p, skv_p = qp.shape[2], kp.shape[2]
    n_q_blocks = sq_p // block_q
    n_kv_blocks = skv_p // block_k

    qp = qp.reshape(b * h, sq_p, d)
    kp = kp.reshape(b * h, skv_p, d)
    vp = vp.reshape(b * h, skv_p, d)
    # per-(example,head) valid key count; None → all real keys valid
    if kv_lens is None:
        lens = jnp.full((b,), s_kv, jnp.int32)
    else:
        lens = jnp.minimum(jnp.asarray(kv_lens, jnp.int32), s_kv)
    lens = jnp.repeat(lens, h)  # (b*h,)

    kernel = functools.partial(
        _attn_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, n_kv_blocks=n_kv_blocks)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qb: (bh, qb, 0)),
            pl.BlockSpec((1, skv_p, d), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((1, skv_p, d), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((1,), lambda bh, qb: (bh,)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qb: (bh, qb, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        interpret=interpret,
    )(qp, kp, vp, lens)
    return out.reshape(b, h, sq_p, d)[:, :, :s_q, :]


def _attention_reference(q, k, v, sm_scale: float, causal: bool,
                         kv_lens=None):
    """Pure-XLA attention (the correctness oracle + backward path)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    s_q, s_k = s.shape[-2], s.shape[-1]
    if causal:
        mask = (jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
                >= jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1))
        s = jnp.where(mask, s, NEG_INF)
    if kv_lens is not None:
        k_pos = jnp.arange(s_k)[None, None, None, :]
        s = jnp.where(k_pos < jnp.asarray(kv_lens)[:, None, None, None],
                      s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def flash_attention(q, k, v, sm_scale: Optional[float] = None,
                    causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None,
                    kv_lens=None) -> jnp.ndarray:
    """Fused attention over (batch, heads, seq, head_dim) tensors.

    ``kv_lens`` (optional int32 [batch]) masks each example's keys past its
    valid length — the padding mask for BERT-style batches and bucketed
    continuous-batch serving.
    """
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if kv_lens is None:
        return _flash_attention_full(q, k, v, scale, causal, block_q,
                                     block_k, interpret)
    return _flash_attention_varlen(q, k, v, jnp.asarray(kv_lens, jnp.int32),
                                   scale, causal, block_q, block_k,
                                   interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_full(q, k, v, sm_scale, causal, block_q, block_k,
                          interpret):
    return _flash_attention_fwd_impl(q, k, v, None, sm_scale, causal,
                                     block_q, block_k, interpret)


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out = _flash_attention_full(q, k, v, sm_scale, causal, block_q, block_k,
                                interpret)
    return out, (q, k, v)


def _bwd(sm_scale, causal, block_q, block_k, interpret, residuals, g):
    # Recompute-based backward in XLA: memory O(S^2) per (b,h) at the
    # training scales this framework targets (ViT/BERT); the fwd kernel
    # stays the serving hot path.
    q, k, v = residuals

    def ref(q_, k_, v_):
        return _attention_reference(q_, k_, v_, sm_scale, causal)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash_attention_full.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_varlen(q, k, v, kv_lens, sm_scale, causal, block_q,
                            block_k, interpret):
    return _flash_attention_fwd_impl(q, k, v, kv_lens, sm_scale, causal,
                                     block_q, block_k, interpret)


def _vfwd(q, k, v, kv_lens, sm_scale, causal, block_q, block_k, interpret):
    out = _flash_attention_varlen(q, k, v, kv_lens, sm_scale, causal,
                                  block_q, block_k, interpret)
    return out, (q, k, v, kv_lens)


def _vbwd(sm_scale, causal, block_q, block_k, interpret, residuals, g):
    import numpy as np

    q, k, v, kv_lens = residuals

    def ref(q_, k_, v_):
        return _attention_reference(q_, k_, v_, sm_scale, causal, kv_lens)

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(g)
    # integer primal → symbolic-zero cotangent (float0)
    d_lens = np.zeros(kv_lens.shape, jax.dtypes.float0)
    return dq, dk, dv, d_lens


_flash_attention_varlen.defvjp(_vfwd, _vbwd)


def mha(x_q, x_kv, params: dict, n_heads: int, causal: bool = False,
        interpret: Optional[bool] = None) -> jnp.ndarray:
    """Full multi-head attention layer over packed projection params.

    ``params`` carries ``wq, wk, wv`` (D, H*Dh) / ``wo`` (H*Dh, D) and
    biases; the core runs through :func:`flash_attention`.
    """
    b, s_q, d_model = x_q.shape
    s_kv = x_kv.shape[1]
    dh = params["wq"].shape[-1] // n_heads

    def proj(x, w, bias):
        y = jnp.einsum("bsd,df->bsf", x, w) + bias
        return y.reshape(b, -1, n_heads, dh).transpose(0, 2, 1, 3)

    q = proj(x_q, params["wq"], params["bq"])
    k = proj(x_kv, params["wk"], params["bk"])
    v = proj(x_kv, params["wv"], params["bv"])
    o = flash_attention(q, k, v, None, causal, 128, 128, interpret)
    o = o.transpose(0, 2, 1, 3).reshape(b, s_q, n_heads * dh)
    return jnp.einsum("bsf,fd->bsd", o, params["wo"]) + params["bo"]
