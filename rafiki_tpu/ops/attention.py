"""Fused multi-head attention (flash-style) as Pallas TPU kernels.

Replaces the cuDNN fused attention the reference's templates get for free
inside TF/PyTorch (SURVEY.md §2.1: the rebuild's native obligation is
XLA/Pallas kernels; ViT attention is the named target). Design:

- Online-softmax streaming over key blocks (never materializes the S×S
  score matrix in HBM): for each query block the kernel keeps running
  (max, sum, weighted-V accumulator) in f32 and rescales as new key blocks
  arrive — the flash-attention recurrence.
- Backward pass: fused Pallas kernels too. The forward saves each row's
  logsumexp (LSE); backward runs two kernels — dQ (grid over query blocks,
  streaming keys) and dK/dV (grid over key blocks, streaming queries) —
  with ``delta = rowsum(dO · O)`` precomputed in XLA. HBM stays O(S·d)
  per (batch, head); the S×S matrix is never materialized.
- Per-row scalars (LSE, delta) are stored replicated across a 128-lane
  trailing dim so every kernel touches only native (sublane, lane) tiles —
  no 1-D refs, no in-kernel transposes (Mosaic-restricted patterns).
- Variable-length batches: ``kv_lens`` rides in as a scalar-prefetch
  operand (SMEM), read per grid row to bound the key loop and mask pads.
- Block sizes default to 128 to match MXU tiling; inputs are padded to
  block multiples by the wrapper. f32 accumulation regardless of input
  dtype (bf16 in, bf16 out, f32 math). Off-TPU the default dispatch uses
  the equivalent pure-XLA path (fast on CPU); the kernel-equivalence
  tests force the kernels through the Pallas interpreter with
  ``interpret=True``.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

from rafiki_tpu.ops.common import use_xla_fallback

NEG_INF = -1e30
# LSE written for rows whose every key is masked: exp(s - 1e30) == 0 for
# any finite score, so such rows contribute exactly zero gradient.
LSE_MASKED = 1e30
# Per-row scalars are replicated across this many lanes (one f32 vreg lane
# dim) so kernels only ever see (sublane, lane)-tiled 2-D blocks.
LANES = 128
# Auto-dispatch (interpret=None) routes sequences at or below this length
# to the pure-XLA path EVEN ON TPU: measured on a v5e chip (ViT-B/16
# train step, seq 197 → padded 256, bs 64), XLA's fused attention beats
# the Pallas kernels 811 vs 578 samples/s — at short seq the O(S²) score
# matrix the flash recurrence exists to avoid fits easily in
# VMEM-friendly fusions, and the kernel's grid/loop overhead dominates.
# The default stays at the measured crossover region (256); above it the
# kernels run, since the XLA path materializes (B, H, S, S) f32
# scores and an unmeasured win is not worth an OOM regression. Override
# with RAFIKI_XLA_SHORT_SEQ (0 disables the short-seq route entirely);
# explicit interpret=False always forces Mosaic lowering.
XLA_SHORT_SEQ = int(os.environ.get("RAFIKI_XLA_SHORT_SEQ", "256"))
# Fleet-applicable default for flash_attention's block_h (multi-head-
# per-program forward): callers that don't pass block_h explicitly pick
# this up, so a hardware sweep win (scripts/tune_attention_tpu.py) can
# be applied to every template without code edits — e.g.
# RAFIKI_ATTN_BLOCK_H=4 flips ViT/BERT onto the mh kernels (and, per
# the dispatch rule below, off the short-seq XLA route). Default 1 =
# per-head programs, today's measured-best configuration.
ATTN_BLOCK_H = max(1, int(os.environ.get("RAFIKI_ATTN_BLOCK_H", "1")))

# (block_h, heads) combos already warned about by the env-default
# divisibility fallback below — warn once per shape, not per call
_ENV_BLOCK_H_WARNED = set()


def _env_block_h(heads: int) -> int:
    """Resolve the env-derived block_h default against this call's
    LOCAL head count. The fleet default is tuned on whole models, but
    ulysses/ring inner calls see heads/tp/sp — a value that doesn't
    divide the local count must degrade to per-head programs (with one
    warning per shape), not hard-fail a template that never asked for
    head tiling. An EXPLICIT block_h keeps the hard ValueError: that is
    a deliberate kernel-tuning choice whose silent fallback would
    invalidate a sweep."""
    block_h = ATTN_BLOCK_H
    if block_h > 1 and heads % block_h:
        key = (block_h, heads)
        if key not in _ENV_BLOCK_H_WARNED:
            _ENV_BLOCK_H_WARNED.add(key)
            import logging

            logging.getLogger(__name__).warning(
                "RAFIKI_ATTN_BLOCK_H=%d does not divide the local head "
                "count (%d); falling back to block_h=1 for this shape",
                block_h, heads)
        return 1
    return block_h


def _attn_fwd_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *lse_refs,
                     sm_scale: float, causal: bool, block_q: int,
                     block_k: int, n_kv_blocks: int):
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qb = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (block_q, d)
    kv_len = len_ref[bh]  # this example's valid key count (pads masked out)

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (block_q, block_k)

        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    # skip key blocks that are fully masked: past this example's kv_len,
    # and (causal) strictly after this query block
    n_blocks = jnp.minimum(
        jnp.asarray(n_kv_blocks, jnp.int32),
        (kv_len + block_k - 1) // block_k)
    if causal:
        n_blocks = jnp.minimum(
            n_blocks, (qb * block_q + block_q + block_k - 1) // block_k)
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    if lse_refs:  # training path only; serving skips the residual write
        lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)),
                        LSE_MASKED)
        lse_refs[0][0] = jax.lax.broadcast_in_dim(
            lse, (block_q, LANES), (0, 1))


def _attn_fwd_mh_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *lse_refs,
                        sm_scale: float, causal: bool, block_h: int,
                        block_q: int, block_k: int, n_kv_blocks: int):
    """Multi-head-per-program forward: each grid step owns ``block_h``
    consecutive (batch, head) rows — batched MXU matmuls amortize the
    per-program grid/DMA overhead that dominates at SHORT sequences,
    where the single-head grid runs thousands of tiny programs (the
    VERDICT r4 seq<=256 regime). All rows in a tile belong to one
    example (callers enforce ``h % block_h == 0``), so they share one
    ``kv_len``. Math is identical to :func:`_attn_fwd_kernel` with a
    leading head-tile dim."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qb = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * sm_scale  # (block_h, bq, d)
    kv_len = len_ref[bh * block_h]  # whole tile = one example's heads

    m0 = jnp.full((block_h, block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_h, block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_h, block_q, q.shape[-1]), jnp.float32)

    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[:, pl.ds(kb * block_k, block_k), :].astype(
            jnp.float32)
        v_blk = v_ref[:, pl.ds(kb * block_k, block_k), :].astype(
            jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)  # (bh, bq, bk)

        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask[None, :, :], s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    n_blocks = jnp.minimum(
        jnp.asarray(n_kv_blocks, jnp.int32),
        (kv_len + block_k - 1) // block_k)
    if causal:
        n_blocks = jnp.minimum(
            n_blocks, (qb * block_q + block_q + block_k - 1) // block_k)
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    if lse_refs:  # training path only; serving skips the residual write
        lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)),
                        LSE_MASKED)
        lse_refs[0][...] = jnp.broadcast_to(
            lse, (block_h, block_q, LANES))


def _attn_bwd_dq_kernel(len_ref, q_ref, g_ref, lse_ref, delta_ref, k_ref,
                        v_ref, dq_ref, *, sm_scale: float, causal: bool,
                        block_q: int, block_k: int, n_kv_blocks: int):
    """dQ for one query block: stream key blocks, accumulate ds·K.

    Requires ``block_k == LANES`` so the lane-replicated LSE/delta tiles
    line up elementwise with the (block_q, block_k) score tile.
    """
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qb = pl.program_id(1)
    kv_len = len_ref[bh]
    q = q_ref[0].astype(jnp.float32)      # (block_q, d)
    g = g_ref[0].astype(jnp.float32)      # (block_q, d)
    lse = lse_ref[0]                      # (block_q, LANES) f32
    delta = delta_ref[0]                  # (block_q, LANES) f32

    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kb, acc):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)                                # (bq, bk)
        dp = jax.lax.dot_general(
            g, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk)
        ds = p * (dp - delta) * sm_scale
        return acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, d)

    n_blocks = jnp.minimum(
        jnp.asarray(n_kv_blocks, jnp.int32),
        (kv_len + block_k - 1) // block_k)
    if causal:
        n_blocks = jnp.minimum(
            n_blocks, (qb * block_q + block_q + block_k - 1) // block_k)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    acc = jax.lax.fori_loop(0, n_blocks, body, acc0)
    dq_ref[0] = acc.astype(dq_ref.dtype)


def _attn_bwd_dkv_kernel(len_ref, q_ref, g_ref, lse_ref, delta_ref, k_ref,
                         v_ref, dk_ref, dv_ref, *, sm_scale: float,
                         causal: bool, block_q: int, block_k: int,
                         n_q_blocks: int):
    """dK/dV for one key block: stream query blocks, accumulate pᵀ·dO and
    dsᵀ·Q. Causal skips query blocks strictly above the diagonal."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    kb = pl.program_id(1)
    kv_len = len_ref[bh]
    k_blk = k_ref[0].astype(jnp.float32)  # (block_k, d)
    v_blk = v_ref[0].astype(jnp.float32)  # (block_k, d)

    k_pos = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        g_blk = g_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :]    # (bq, LANES)
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), :]
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # (bq, bk)
        q_pos = qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(
            p, g_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, d)
        dp = jax.lax.dot_general(
            g_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bq, bk)
        ds = p * (dp - delta) * sm_scale
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, d)
        return dk, dv

    # causal: the first query row that can see key kb*block_k is that same
    # position, so start at its query block
    start = (kb * block_k) // block_q if causal else 0
    # key block entirely past kv_len → every p underflows to zero; skip
    # the whole query loop instead of multiplying zeros on the MXU
    stop = jnp.where(kb * block_k < kv_len,
                     jnp.asarray(n_q_blocks, jnp.int32),
                     jnp.asarray(start, jnp.int32))
    z = jnp.zeros((block_k, k_blk.shape[-1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, stop, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    return jax.default_backend() != "tpu" if interpret is None else interpret


def _prep_lens(kv_lens, b: int, h: int, s_kv: int) -> jnp.ndarray:
    """(b,) valid-key counts → (b*h,) int32 scalar-prefetch operand."""
    if kv_lens is None:
        lens = jnp.full((b,), s_kv, jnp.int32)
    else:
        lens = jnp.minimum(jnp.asarray(kv_lens, jnp.int32), s_kv)
    return jnp.repeat(lens, h)


def _flash_attention_fwd_impl(q, k, v, kv_lens, sm_scale: float,
                              causal: bool, block_q: int, block_k: int,
                              interpret: Optional[bool], *,
                              with_lse: bool = False, block_h: int = 1):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    interpret = _resolve_interpret(interpret)
    if block_h < 1:
        raise ValueError(f"block_h={block_h} must be >= 1")
    if block_h > 1 and h % block_h:
        raise ValueError(
            f"block_h={block_h} must divide heads ({h}): a head tile "
            "spanning two examples would mix their kv_lens")

    qp = _pad_to(q, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    sq_p, skv_p = qp.shape[2], kp.shape[2]
    n_q_blocks = sq_p // block_q
    n_kv_blocks = skv_p // block_k

    qp = qp.reshape(b * h, sq_p, d)
    kp = kp.reshape(b * h, skv_p, d)
    vp = vp.reshape(b * h, skv_p, d)
    lens = _prep_lens(kv_lens, b, h, s_kv)

    if block_h > 1:
        kernel = functools.partial(
            _attn_fwd_mh_kernel, sm_scale=sm_scale, causal=causal,
            block_h=block_h, block_q=block_q, block_k=block_k,
            n_kv_blocks=n_kv_blocks)
    else:
        kernel = functools.partial(
            _attn_fwd_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, n_kv_blocks=n_kv_blocks)
    out_specs = [
        pl.BlockSpec((block_h, block_q, d),
                     lambda bh, qb, lens: (bh, qb, 0)),
    ]
    out_shape = [jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype)]
    if with_lse:  # residual for the fused backward (training path only)
        out_specs.append(pl.BlockSpec((block_h, block_q, LANES),
                                      lambda bh, qb, lens: (bh, qb, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, sq_p, LANES), jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h // block_h, n_q_blocks),
        in_specs=[
            pl.BlockSpec((block_h, block_q, d),
                         lambda bh, qb, lens: (bh, qb, 0)),
            pl.BlockSpec((block_h, skv_p, d),
                         lambda bh, qb, lens: (bh, 0, 0)),
            pl.BlockSpec((block_h, skv_p, d),
                         lambda bh, qb, lens: (bh, 0, 0)),
        ],
        out_specs=out_specs,
    )
    res = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(lens, qp, kp, vp)
    out = res[0].reshape(b, h, sq_p, d)[:, :, :s_q, :]
    if with_lse:
        return out, res[1]  # lse stays padded/lane-replicated for the bwd
    return out


def _flash_attention_bwd_impl(q, k, v, kv_lens, o, lse, g, sm_scale: float,
                              causal: bool, block_q: int, block_k: int,
                              interpret: Optional[bool], g_lse=None):
    """Fused dq/dk/dv. ``lse`` is the (b*h, sq_padded, LANES) residual.

    ``g_lse`` (optional, (b, h, s_q) f32) is the cotangent of the LSE
    output when the caller consumed :func:`flash_attention_lse`. It folds
    into the existing kernels for free: with p = exp(s − lse),
    ∂lse/∂s = p, so ds = p·(dp − delta + g_lse) — i.e. the kernels run
    unchanged with delta' = delta − g_lse. (dV has no lse term.)"""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # The backward always tiles keys at LANES so the lane-replicated
    # LSE/delta tiles line up elementwise with the (block_q, block_k)
    # score tile — the caller's block_k only shapes the forward. block_q
    # must stay the forward's: the saved lse is padded at its granularity.
    block_k = LANES
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    interpret = _resolve_interpret(interpret)

    qp = _pad_to(q, 2, block_q).reshape(b * h, -1, d)
    kp = _pad_to(k, 2, block_k).reshape(b * h, -1, d)
    vp = _pad_to(v, 2, block_k).reshape(b * h, -1, d)
    gp = _pad_to(g, 2, block_q).reshape(b * h, -1, d)
    op = _pad_to(o, 2, block_q).reshape(b * h, -1, d)
    sq_p, skv_p = qp.shape[1], kp.shape[1]
    n_q_blocks = sq_p // block_q
    n_kv_blocks = skv_p // block_k
    lens = _prep_lens(kv_lens, b, h, s_kv)

    # delta_i = Σ_d dO_id · O_id, lane-replicated like the LSE
    delta = jnp.sum(gp.astype(jnp.float32) * op.astype(jnp.float32),
                    axis=-1, keepdims=True)
    if g_lse is not None:
        glp = _pad_to(g_lse.astype(jnp.float32).reshape(b * h, s_q, 1),
                      1, block_q)
        delta = delta - glp
    delta = jnp.broadcast_to(delta, (b * h, sq_p, LANES))

    dq_kernel = functools.partial(
        _attn_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, n_kv_blocks=n_kv_blocks)
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, n_q_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qb, lens: (bh, qb, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qb, lens: (bh, qb, 0)),
            pl.BlockSpec((1, block_q, LANES),
                         lambda bh, qb, lens: (bh, qb, 0)),
            pl.BlockSpec((1, block_q, LANES),
                         lambda bh, qb, lens: (bh, qb, 0)),
            pl.BlockSpec((1, skv_p, d), lambda bh, qb, lens: (bh, 0, 0)),
            pl.BlockSpec((1, skv_p, d), lambda bh, qb, lens: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qb, lens: (bh, qb, 0)),
    )
    dq = pl.pallas_call(
        dq_kernel, grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        interpret=interpret,
    )(lens, qp, gp, lse, delta, kp, vp)

    dkv_kernel = functools.partial(
        _attn_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, n_q_blocks=n_q_blocks)
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, n_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, sq_p, d), lambda bh, kb, lens: (bh, 0, 0)),
            pl.BlockSpec((1, sq_p, d), lambda bh, kb, lens: (bh, 0, 0)),
            pl.BlockSpec((1, sq_p, LANES), lambda bh, kb, lens: (bh, 0, 0)),
            pl.BlockSpec((1, sq_p, LANES), lambda bh, kb, lens: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, kb, lens: (bh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, kb, lens: (bh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, kb, lens: (bh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, kb, lens: (bh, kb, 0)),
        ],
    )
    dk, dv = pl.pallas_call(
        dkv_kernel, grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, skv_p, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, skv_p, d), v.dtype),
        ],
        interpret=interpret,
    )(lens, qp, gp, lse, delta, kp, vp)

    dq = dq.reshape(b, h, sq_p, d)[:, :, :s_q, :]
    dk = dk.reshape(b, h, skv_p, d)[:, :, :s_kv, :]
    dv = dv.reshape(b, h, skv_p, d)[:, :, :s_kv, :]
    return dq, dk, dv


def _attention_reference(q, k, v, sm_scale: float, causal: bool,
                         kv_lens=None):
    """Pure-XLA attention (correctness oracle AND the off-TPU fast path).

    Matches the kernels bit-for-behavior on fully masked rows too: a row
    whose every key is masked (kv_len == 0) outputs exact zeros with zero
    gradient, like the kernels' ``LSE_MASKED`` path — not softmax's
    uniform-weights answer.
    """
    if kv_lens is None:  # one oracle: the lse twin owns the shared math
        out, _ = _attention_reference_lse(q, k, v, sm_scale, causal)
        return out
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    s_q, s_k = s.shape[-2], s.shape[-1]
    if causal:
        mask = (jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
                >= jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1))
        s = jnp.where(mask, s, NEG_INF)
    k_pos = jnp.arange(s_k)[None, None, None, :]
    s = jnp.where(k_pos < jnp.asarray(kv_lens)[:, None, None, None],
                  s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    nonempty = (jnp.asarray(kv_lens) > 0)[:, None, None, None]
    p = jnp.where(nonempty, p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def flash_attention(q, k, v, sm_scale: Optional[float] = None,
                    causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None,
                    kv_lens=None,
                    block_h: Optional[int] = None) -> jnp.ndarray:
    """Fused attention over (batch, heads, seq, head_dim) tensors.

    ``kv_lens`` (optional int32 [batch]) masks each example's keys past its
    valid length — the padding mask for BERT-style batches and bucketed
    continuous-batch serving. Differentiable end-to-end via the fused
    Pallas backward kernels.

    ``block_h`` (>1) runs the multi-head-per-program FORWARD kernel:
    each grid step owns that many consecutive heads of one example
    (``heads % block_h == 0``), batching their matmuls in one program —
    the short-sequence lever (VERDICT r4 item 3), where the per-head
    grid's thousands of tiny programs pay more in grid/DMA overhead
    than compute. Because that is exactly the regime the
    ``XLA_SHORT_SEQ`` route covers, an explicit ``block_h>1``
    DISABLES the short-seq XLA route (on TPU) rather than being
    silently dropped by it. The backward keeps the per-head kernels
    (its grids are fewer and larger). Sweep on hardware with
    ``scripts/tune_attention_tpu.py``.

    Dispatch: with ``interpret=None`` (the default used by every model
    template) the Pallas kernels run only on a real TPU backend AND at
    sequence lengths above ``XLA_SHORT_SEQ`` — short sequences measure
    faster through XLA's own fusions even on TPU (see the constant's
    note), and off-TPU the pure-XLA path is orders of magnitude faster
    than the Pallas interpreter. Pass ``interpret=True`` to force the
    kernels through the interpreter (the kernel-equivalence tests do),
    or ``interpret=False`` for Mosaic lowering.
    """
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if block_h is None:  # env-tunable fleet default (RAFIKI_ATTN_BLOCK_H)
        block_h = _env_block_h(q.shape[1])
    # an explicit block_h>1 is a deliberate kernel-tuning choice FOR the
    # short-seq regime — it must not be silently dropped by the
    # short-seq XLA route (off-TPU fallback still applies)
    short = (interpret is None and block_h == 1
             and max(q.shape[2], k.shape[2]) <= XLA_SHORT_SEQ)
    if short or use_xla_fallback(interpret):
        lens = None if kv_lens is None else jnp.asarray(kv_lens, jnp.int32)
        return _attention_reference(q, k, v, scale, causal, lens)
    if kv_lens is None:
        return _flash_attention_full(q, k, v, scale, causal, block_q,
                                     block_k, interpret, block_h)
    return _flash_attention_varlen(q, k, v, jnp.asarray(kv_lens, jnp.int32),
                                   scale, causal, block_q, block_k,
                                   interpret, block_h)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_attention_full(q, k, v, sm_scale, causal, block_q, block_k,
                          interpret, block_h):
    return _flash_attention_fwd_impl(q, k, v, None, sm_scale, causal,
                                     block_q, block_k, interpret,
                                     block_h=block_h)


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret, block_h):
    out, lse = _flash_attention_fwd_impl(
        q, k, v, None, sm_scale, causal, block_q, block_k, interpret,
        with_lse=True, block_h=block_h)
    return out, (q, k, v, out, lse)


def _bwd(sm_scale, causal, block_q, block_k, interpret, block_h,
         residuals, g):
    q, k, v, o, lse = residuals
    return _flash_attention_bwd_impl(q, k, v, None, o, lse, g, sm_scale,
                                     causal, block_q, block_k, interpret)


_flash_attention_full.defvjp(_fwd, _bwd)


def _attention_reference_lse(q, k, v, sm_scale: float, causal: bool):
    """XLA twin of :func:`flash_attention_lse` (off-TPU dispatch). Plain
    jnp math, so autodiff handles the LSE cotangent natively."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        s_q, s_k = s.shape[-2], s.shape[-1]
        mask = (jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
                >= jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1))
        s = jnp.where(mask, s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bhkd->bhqd", p,
                     v.astype(jnp.float32)).astype(q.dtype)
    return out, lse


def _lse_rows(lse_pad, q_shape):
    """(b*h, sq_padded, LANES) lane-replicated residual → (b, h, s_q)."""
    b, h, s_q, _ = q_shape
    return lse_pad[:, :s_q, 0].reshape(b, h, s_q)


def flash_attention_lse(q, k, v, sm_scale: Optional[float] = None,
                        causal: bool = False, block_q: int = 128,
                        block_k: int = 128,
                        interpret: Optional[bool] = None):
    """Like :func:`flash_attention` but returns ``(out, lse)`` where
    ``lse[b, h, i]`` is the log-sum-exp of row i's (scaled, masked)
    scores — the residual blockwise consumers (ring attention) need to
    combine per-block outputs exactly: out = Σ_blocks e^{lse_s − m}·out_s
    normalized. Differentiable in ``out`` AND ``lse``. Dispatch: Pallas
    on TPU at ANY length, XLA twin off-TPU — unlike
    :func:`flash_attention` there is NO short-seq XLA routing here: the
    callers (ring attention) hold long sequences by construction, and
    their per-block lse/combine math must come from one code path.
    No ``kv_lens`` support: a fully-masked row's LSE sentinel
    (+``LSE_MASKED``) would poison a cross-block max-combine."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if use_xla_fallback(interpret):
        return _attention_reference_lse(q, k, v, scale, causal)
    return _flash_attention_full_lse(q, k, v, scale, causal, block_q,
                                     block_k, interpret)


def flash_attention_block_bwd(q, k, v, o, lse, g, sm_scale: float,
                              causal: bool = False, block_q: int = 128,
                              block_k: int = 128,
                              interpret: Optional[bool] = None):
    """One block's contribution to the GLOBAL attention backward.

    For blockwise/ring consumers: given this block's q/k/v, the globally
    combined output ``o`` and row log-sum-exp ``lse`` (b, h, s_q) over
    ALL blocks, and the output cotangent ``g``, returns (dq, dk, dv) for
    this block — ``p = exp(s − lse)`` are the block's columns of the
    global attention matrix, so summing dq over blocks and routing each
    dk/dv to its block reconstructs the exact full backward. Dispatch
    matches :func:`flash_attention_lse` (Pallas on TPU at any length,
    XLA twin off-TPU — no short-seq routing; the lse/combine math must
    come from one code path). f32 outputs (callers accumulate across
    blocks)."""
    if use_xla_fallback(interpret):
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * sm_scale
        if causal:
            s_q, s_k = s.shape[-2], s.shape[-1]
            mask = (jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
                    >= jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1))
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])
        gf = g.astype(jnp.float32)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v.astype(jnp.float32))
        delta = jnp.sum(gf * o.astype(jnp.float32), axis=-1)
        ds = p * (dp - delta[..., None]) * sm_scale
        dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
        return dq, dk, dv
    b, h, s_q, _ = q.shape
    lse_pad = _pad_to(
        jnp.broadcast_to(lse.astype(jnp.float32).reshape(b * h, s_q, 1),
                         (b * h, s_q, LANES)), 1, block_q)
    dq, dk, dv = _flash_attention_bwd_impl(
        q, k, v, None, o, lse_pad, g, sm_scale, causal, block_q, block_k,
        interpret)
    return (dq.astype(jnp.float32), dk.astype(jnp.float32),
            dv.astype(jnp.float32))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_full_lse(q, k, v, sm_scale, causal, block_q, block_k,
                              interpret):
    out, lse_pad = _flash_attention_fwd_impl(
        q, k, v, None, sm_scale, causal, block_q, block_k, interpret,
        with_lse=True)
    return out, _lse_rows(lse_pad, q.shape)


def _lse_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse_pad = _flash_attention_fwd_impl(
        q, k, v, None, sm_scale, causal, block_q, block_k, interpret,
        with_lse=True)
    return (out, _lse_rows(lse_pad, q.shape)), (q, k, v, out, lse_pad)


def _lse_bwd(sm_scale, causal, block_q, block_k, interpret, residuals, gs):
    q, k, v, o, lse_pad = residuals
    g_out, g_lse = gs
    return _flash_attention_bwd_impl(q, k, v, None, o, lse_pad, g_out,
                                     sm_scale, causal, block_q, block_k,
                                     interpret, g_lse=g_lse)


_flash_attention_full_lse.defvjp(_lse_fwd, _lse_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_attention_varlen(q, k, v, kv_lens, sm_scale, causal, block_q,
                            block_k, interpret, block_h):
    return _flash_attention_fwd_impl(q, k, v, kv_lens, sm_scale, causal,
                                     block_q, block_k, interpret,
                                     block_h=block_h)


def _vfwd(q, k, v, kv_lens, sm_scale, causal, block_q, block_k, interpret,
          block_h):
    out, lse = _flash_attention_fwd_impl(
        q, k, v, kv_lens, sm_scale, causal, block_q, block_k, interpret,
        with_lse=True, block_h=block_h)
    return out, (q, k, v, kv_lens, out, lse)


def _vbwd(sm_scale, causal, block_q, block_k, interpret, block_h,
          residuals, g):
    import numpy as np

    q, k, v, kv_lens, o, lse = residuals
    dq, dk, dv = _flash_attention_bwd_impl(
        q, k, v, kv_lens, o, lse, g, sm_scale, causal, block_q, block_k,
        interpret)
    # integer primal → symbolic-zero cotangent (float0)
    d_lens = np.zeros(kv_lens.shape, jax.dtypes.float0)
    return dq, dk, dv, d_lens


_flash_attention_varlen.defvjp(_vfwd, _vbwd)


def mha(x_q, x_kv, params: dict, n_heads: int, causal: bool = False,
        interpret: Optional[bool] = None) -> jnp.ndarray:
    """Full multi-head attention layer over packed projection params.

    ``params`` carries ``wq, wk, wv`` (D, H*Dh) / ``wo`` (H*Dh, D) and
    biases; the core runs through :func:`flash_attention`.
    """
    b, s_q, d_model = x_q.shape
    s_kv = x_kv.shape[1]
    dh = params["wq"].shape[-1] // n_heads

    def proj(x, w, bias):
        y = jnp.einsum("bsd,df->bsf", x, w) + bias
        return y.reshape(b, -1, n_heads, dh).transpose(0, 2, 1, 3)

    q = proj(x_q, params["wq"], params["bq"])
    k = proj(x_kv, params["wk"], params["bk"])
    v = proj(x_kv, params["wv"], params["bv"])
    o = flash_attention(q, k, v, None, causal, 128, 128, interpret)
    o = o.transpose(0, 2, 1, 3).reshape(b, s_q, n_heads * dh)
    return jnp.einsum("bsf,fd->bsd", o, params["wo"]) + params["bo"]
