"""Mixture-of-Experts layer with expert parallelism — the ``ep`` leg.

The reference stack has no MoE; a TPU framework needs one because
expert parallelism is how modern LMs scale parameter count without
scaling per-token FLOPs, and its sharding story is TPU-shaped: experts
live sharded across the mesh and tokens travel to their experts over
ICI. Design (the Shazeer/GShard recipe, XLA-first):

- **Static shapes via capacity.** Each expert processes exactly
  ``capacity = ceil(tokens/E · capacity_factor)`` slots per batch.
  Routing builds DISPATCH/COMBINE tensors (one-hot over (expert,
  slot)), so expert selection is two einsums on the MXU — no gather/
  scatter, no dynamic shapes, nothing XLA can't tile. Overflowing
  tokens are dropped (combine weight 0 → they pass through the
  residual stream untouched), the standard capacity trade.
- **Top-k routing** (k=1 Switch default, k=2 GShard/Mixtral-style with
  pair-renormalized gates) with the load-balancing auxiliary loss from
  the Switch Transformer: ``E · Σ_e fraction_e · prob_e``, minimized at
  uniform routing. The aux loss is returned via a flax
  ``"losses"`` collection so any host module can pick it up with
  ``mutable=["losses"]``.
- **Expert parallelism by annotation:** expert weights are stacked
  ``(E, …)`` arrays; shard dim 0 over the mesh's ``model`` axis
  (``TP_RULES``-style rules match ``"experts"``) and XLA partitions
  the dispatch einsums into the all-to-all + local-expert-compute
  schedule — the same "annotate, let the compiler insert collectives"
  contract every other layer here uses.
- Router math in f32 regardless of compute dtype (softmax over logits
  is precision-sensitive; standard practice).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

#: standard weight on the load-balancing aux loss in the train
#: objective (the Switch Transformer default) — one definition so the
#: template, dryrun, and benches can't drift
MOE_AUX_COEF = 0.01


def router_dispatch(logits: jnp.ndarray, capacity: int, top_k: int = 1
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k capacity routing from ``(T, E)`` router logits
    (``top_k=1`` = Switch, ``top_k=2`` = GShard/Mixtral-style).

    Returns ``(dispatch, combine, aux)``:
    - ``dispatch``: (T, E, C) one-hot — token t occupies slot c of
      expert e (0 rows for dropped/overflow choices);
    - ``combine``: (T, E, C) — dispatch scaled by the token's gate for
      that expert (router probs renormalized over its top-k choices —
      the gradient path back into the router);
    - ``aux``: scalar load-balancing loss (Switch form, over top-1
      assignments).

    Choices fill capacity in priority order (all first choices, then
    all second choices), each within arrival order — deterministic,
    static shapes, one-hot matmul/cumsum math only (MXU/VPU friendly:
    no sorts over the vocab of experts, no dynamic shapes).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # (T, E)
    top_vals, top_idx = jax.lax.top_k(probs, top_k)              # (T, k)
    # gates: Switch (k=1) uses the RAW router prob — renormalizing a
    # single choice would always give 1.0 and cut the router's gradient
    # signal; GShard-style k>1 renormalizes over the chosen set
    if top_k == 1:
        gates = top_vals                                         # (T, 1)
    else:
        gates = top_vals / jnp.maximum(
            jnp.sum(top_vals, axis=-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    filled = jnp.zeros((e,), jnp.float32)  # slots consumed per expert
    for j in range(top_k):  # static, tiny
        onehot = jax.nn.one_hot(top_idx[:, j], e, dtype=jnp.float32)
        # slot index = earlier same-choice tokens + slots already
        # consumed by higher-priority choices
        position = (jnp.cumsum(onehot, axis=0) - onehot
                    + filled[None, :]) * onehot
        keep = (position < capacity)
        kept = onehot * keep
        slot = jax.nn.one_hot(position.astype(jnp.int32), capacity,
                              dtype=jnp.float32)                 # (T,E,C)
        d_j = kept[..., None] * slot
        dispatch = dispatch + d_j
        combine = combine + d_j * gates[:, j, None, None]
        filled = filled + jnp.sum(kept, axis=0)

    # load balance: fraction of tokens whose TOP choice is e × mean
    # router prob for e, scaled by E — 1 at perfectly uniform routing
    top1 = jax.nn.one_hot(top_idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.sum(jnp.mean(top1, axis=0) * jnp.mean(probs, axis=0))
    return dispatch, combine, aux


class MoEFeedForward(nn.Module):
    """MoE FFN: top-k routed SwiGLU experts (``router_top_k``: 1 =
    Switch, 2 = GShard/Mixtral-style).

    Drop-in for a dense FFN over ``(B, S, D)`` activations. Expert
    weights are stacked ``(E, ...)``; shard dim 0 over the mesh's
    ``model`` axis for expert parallelism (``"experts"`` matches the
    Llama ``TP_RULES`` naming contract). Aux loss lands in the
    ``"losses"`` collection under ``"moe_aux"``.
    """

    n_experts: int
    mlp_dim: int
    capacity_factor: float = 1.25
    #: experts per token: 1 = Switch, 2 = GShard/Mixtral-style (gates
    #: renormalized over the chosen pair)
    router_top_k: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, s, d = x.shape
        e, h = self.n_experts, self.mlp_dim
        t = b * s
        capacity = max(1, int(-(-t * self.router_top_k
                                * self.capacity_factor // e)))
        xf = x.reshape(t, d)

        # router in f32 (precision-sensitive softmax over logits)
        wr = self.param("router", nn.initializers.normal(0.02), (d, e))
        logits = xf.astype(jnp.float32) @ wr.astype(jnp.float32)
        dispatch, combine, aux = router_dispatch(
            logits, capacity, top_k=self.router_top_k)
        self.sow("losses", "moe_aux", aux)

        # stacked expert SwiGLU weights — dim 0 is the EXPERT axis the
        # mesh shards (expert parallelism): XLA turns the dispatch
        # einsums into all-to-all + per-device expert compute
        init = nn.initializers.lecun_normal()
        w_gate = self.param("experts_gate", init, (e, d, h))
        w_up = self.param("experts_up", init, (e, d, h))
        w_down = self.param("experts_down", init, (e, h, d))

        cdt = x.dtype if self.dtype is None else self.dtype
        # tokens → expert slots (one-hot matmul, not scatter)
        slots = jnp.einsum("td,tec->ecd", xf.astype(jnp.float32),
                           dispatch).astype(cdt)          # (E, C, D)
        gate = jnp.einsum("ecd,edh->ech", slots, w_gate.astype(cdt))
        up = jnp.einsum("ecd,edh->ech", slots, w_up.astype(cdt))
        out = jnp.einsum("ech,ehd->ecd", nn.silu(gate) * up,
                         w_down.astype(cdt))              # (E, C, D)
        # expert slots → tokens, weighted by router prob; dropped
        # tokens get exact zeros (residual stream carries them)
        y = jnp.einsum("ecd,tec->td", out.astype(jnp.float32),
                       combine)
        return y.reshape(b, s, d).astype(x.dtype)


def moe_aux_loss(mutated_collections: dict) -> jnp.ndarray:
    """Sum every sown ``moe_aux`` scalar from a ``mutable=["losses"]``
    apply — the term the train loss adds (scaled by ~1e-2)."""
    total = jnp.asarray(0.0, jnp.float32)
    losses = mutated_collections.get("losses", {})

    def visit(node):
        nonlocal total
        if isinstance(node, dict):
            for k, v in node.items():
                if k == "moe_aux":
                    for leaf in jax.tree_util.tree_leaves(v):
                        total = total + jnp.asarray(leaf, jnp.float32)
                else:
                    visit(v)

    visit(losses)
    return total
