"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The second long-context strategy next to ring attention
(``ops/ring_attention.py``), trading differently: instead of rotating
K/V blocks P times around the ICI ring (P collectives of size L/P per
device), Ulysses does TWO all-to-alls — swap the sharded axis from
sequence to heads, run ordinary FULL-sequence attention on each
device's head group, swap back. Per-device memory for scores is
O(h/P · L²/block) with flash attention (streamed), communication is
2 all-to-alls regardless of P, and the attention itself is exactly the
single-device kernel — so the Pallas flash path applies unchanged on
TPU.

Pick Ulysses when heads divide the mesh axis (h % P == 0) and the full
sequence fits one device's HBM once heads are split; pick ring
attention when sequence length itself is the constraint. Both are
``shard_map`` + standard XLA collectives — no hand-written transport —
and differentiable end-to-end (``all_to_all`` has a transpose rule).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh, axis: str,
                      sm_scale: Optional[float] = None,
                      causal: bool = False,
                      batch_axis: Optional[str] = None) -> jnp.ndarray:
    """Attention over (batch, heads, seq, head_dim) with ``seq`` sharded
    on ``mesh[axis]``; heads must be divisible by that axis size.

    Internally: all-to-all to (batch, heads/P, SEQ, head_dim) — full
    sequence, split heads — ordinary attention (Pallas flash on TPU,
    pure XLA elsewhere, via :func:`rafiki_tpu.ops.attention
    .flash_attention`), then the inverse all-to-all. Output sharding
    matches the inputs'. Differentiable end-to-end.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from rafiki_tpu.ops.attention import flash_attention
    from rafiki_tpu.ops.common import shard_map_kernels

    n_par = mesh.shape[axis]
    h = q.shape[1]
    if h % n_par:
        raise ValueError(
            f"ulysses needs heads % mesh[{axis!r}] == 0; got {h} heads "
            f"over {n_par} devices (use ring_attention instead)")
    scale = (sm_scale if sm_scale is not None
             else 1.0 / math.sqrt(q.shape[-1]))
    seq_spec = P(batch_axis, None, axis, None)

    @functools.partial(
        shard_map_kernels, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec)
    def _ulysses(ql, kl, vl):
        # local (b, h, L/P, d) → (b, h/P, L, d): split heads, gather seq
        def swap(t):
            return jax.lax.all_to_all(t, axis, split_axis=1,
                                      concat_axis=2, tiled=True)

        qh, kh, vh = swap(ql), swap(kl), swap(vl)
        # full-sequence attention on this device's head group — the
        # ordinary kernel, so causal masks need no offset bookkeeping
        oh = flash_attention(qh, kh, vh, sm_scale=scale, causal=causal)
        # inverse: split seq back out, gather this device's heads
        return jax.lax.all_to_all(oh, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    shard = NamedSharding(mesh, seq_spec)
    return _ulysses(jax.device_put(q, shard), jax.device_put(k, shard),
                    jax.device_put(v, shard))
