"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The second long-context strategy next to ring attention
(``ops/ring_attention.py``), trading differently: instead of rotating
K/V blocks P times around the ICI ring (P collectives of size L/P per
device), Ulysses does TWO all-to-alls — swap the sharded axis from
sequence to heads, run ordinary FULL-sequence attention on each
device's head group, swap back. Per-device memory for scores is
O(h/P · L²/block) with flash attention (streamed), communication is
2 all-to-alls regardless of P, and the attention itself is exactly the
single-device kernel — so the Pallas flash path applies unchanged on
TPU.

Pick Ulysses when heads divide the mesh axis (h % P == 0) and the full
sequence fits one device's HBM once heads are split; pick ring
attention when sequence length itself is the constraint. Both are
``shard_map`` + standard XLA collectives — no hand-written transport —
and differentiable end-to-end (``all_to_all`` has a transpose rule).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh, axis: str,
                      sm_scale: Optional[float] = None,
                      causal: bool = False,
                      batch_axis: Optional[str] = None,
                      head_axis: Optional[str] = None) -> jnp.ndarray:
    """Attention over (batch, heads, seq, head_dim) with ``seq`` sharded
    on ``mesh[axis]``; heads must be divisible by that axis size.

    Internally: all-to-all to (batch, heads/P, SEQ, head_dim) — full
    sequence, split heads — ordinary attention (Pallas flash on TPU,
    pure XLA elsewhere, via :func:`rafiki_tpu.ops.attention
    .flash_attention`), then the inverse all-to-all. Output sharding
    matches the inputs'. Differentiable end-to-end.

    GQA-aware: ``k``/``v`` may carry ``kv_heads = heads / rep`` heads
    (query group g attends kv head ``g // rep``, the ``jnp.repeat``
    convention). When ``kv_heads`` also divides the axis, the SMALL
    K/V ride the all-to-alls (``rep``× less collective volume) and
    each device repeats its landed kv chunk locally — exact, because
    contiguous head tiling sends q heads ``[p·h/P, (p+1)·h/P)`` and kv
    heads ``[p·h_kv/P, (p+1)·h_kv/P)`` to the same device p, and
    ``h/P = rep · h_kv/P`` makes the local repeat the right pairing.
    Otherwise K/V repeat before the swap (plain behavior).

    Tensor-parallel composition: with ``head_axis`` set, the HEAD dim
    is additionally sharded over that mesh axis (Megatron TP keeps
    each attention head whole on one model shard), and the ulysses
    swap runs WITHIN each TP head group — the all-to-alls ride
    ``mesh[axis]`` only, so sp and tp traffic never mix. Requires
    ``heads/tp % sp == 0`` (and ``kv_heads % tp == 0`` so the GQA
    pairing stays aligned per shard).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from rafiki_tpu.ops.attention import flash_attention
    from rafiki_tpu.ops.common import (gqa_repeat_factor,
                                       shard_map_kernels)

    n_par = mesh.shape[axis]
    h, h_kv = q.shape[1], k.shape[1]
    rep = gqa_repeat_factor(h, h_kv)
    tp = mesh.shape[head_axis] if head_axis is not None else 1
    if h % tp or h_kv % tp:
        raise ValueError(
            f"ulysses with head_axis needs heads ({h}) and kv_heads "
            f"({h_kv}) divisible by mesh[{head_axis!r}] ({tp})")
    h_local, h_kv_local = h // tp, h_kv // tp
    if h_local % n_par:
        raise ValueError(
            f"ulysses needs per-shard heads % mesh[{axis!r}] == 0; got "
            f"{h_local} heads over {n_par} devices "
            "(use ring_attention instead)")
    small_swap = rep > 1 and h_kv_local % n_par == 0
    scale = (sm_scale if sm_scale is not None
             else 1.0 / math.sqrt(q.shape[-1]))
    seq_spec = P(batch_axis, head_axis, axis, None)

    @functools.partial(
        shard_map_kernels, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec)
    def _ulysses(ql, kl, vl):
        # local (b, h, L/P, d) → (b, h/P, L, d): split heads, gather seq
        def swap(t):
            return jax.lax.all_to_all(t, axis, split_axis=1,
                                      concat_axis=2, tiled=True)

        def kv(t):
            if small_swap:  # all-to-all the small tensor, repeat after
                return jnp.repeat(swap(t), rep, axis=1)
            return swap(jnp.repeat(t, rep, axis=1) if rep > 1 else t)

        qh, kh, vh = swap(ql), kv(kl), kv(vl)
        # full-sequence attention on this device's head group — the
        # ordinary kernel, so causal masks need no offset bookkeeping
        oh = flash_attention(qh, kh, vh, sm_scale=scale, causal=causal)
        # inverse: split seq back out, gather this device's heads
        return jax.lax.all_to_all(oh, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    shard = NamedSharding(mesh, seq_spec)
    return _ulysses(jax.device_put(q, shard), jax.device_put(k, shard),
                    jax.device_put(v, shard))
