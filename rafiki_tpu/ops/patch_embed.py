"""ViT patch embedding as a fused Pallas matmul kernel.

The patch-embed conv (stride = kernel = patch size) is exactly a reshape
into flattened patches followed by one dense projection. XLA's layout ops
do the reshape for free; the Pallas kernel fuses the (N_patches, P·P·C) ×
(P·P·C, D) projection with the bias add, tiled to the MXU (BASELINE.md
config #3 names this kernel). f32 accumulation, bf16-friendly.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _matmul_bias_kernel(x_ref, w_ref, b_ref, o_ref, *, n_k_blocks: int,
                        block_k: int):
    from jax.experimental import pallas as pl

    acc = jnp.zeros(o_ref.shape, jnp.float32)

    def body(kb, acc):
        x_blk = x_ref[:, pl.ds(kb * block_k, block_k)].astype(jnp.float32)
        w_blk = w_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        return acc + jax.lax.dot_general(
            x_blk, w_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    acc = jax.lax.fori_loop(0, n_k_blocks, body, acc)
    o_ref[:, :] = (acc + b_ref[0].astype(jnp.float32)).astype(o_ref.dtype)


def matmul_bias(x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray,
                block_m: int = 256, block_n: int = 256, block_k: int = 512,
                interpret: Optional[bool] = None) -> jnp.ndarray:
    """Tiled ``x @ w + b`` on the MXU; pads every dim to block multiples.

    Off-TPU with ``interpret=None`` this routes to plain XLA ``x @ w + b``
    (the interpreter is test-only, forced via ``interpret=True``).
    """
    from jax.experimental import pallas as pl

    from rafiki_tpu.ops.common import use_xla_fallback

    m, k = x.shape
    k2, n = w.shape
    assert k == k2 and b.shape == (n,), (x.shape, w.shape, b.shape)
    if use_xla_fallback(interpret):
        # f32 math like the kernel, cast back to the input dtype
        return (x.astype(jnp.float32) @ w.astype(jnp.float32)
                + b.astype(jnp.float32)).astype(x.dtype)
    interpret = bool(interpret)

    block_m = min(block_m, max(8, m))
    block_n = min(block_n, max(128, n))
    block_k = min(block_k, max(128, k))
    pad_m, pad_n, pad_k = ((-m) % block_m, (-n) % block_n, (-k) % block_k)
    xp = jnp.pad(x, ((0, pad_m), (0, pad_k)))
    wp = jnp.pad(w, ((0, pad_k), (0, pad_n)))
    bp = jnp.pad(b, (0, pad_n)).reshape(1, -1)
    mp, kp, np_ = m + pad_m, k + pad_k, n + pad_n

    kernel = functools.partial(_matmul_bias_kernel,
                               n_k_blocks=kp // block_k, block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=(mp // block_m, np_ // block_n),
        in_specs=[
            pl.BlockSpec((block_m, kp), lambda i, j: (i, 0)),
            pl.BlockSpec((kp, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        interpret=interpret,
    )(xp, wp, bp)
    return out[:m, :n]


def extract_patches(images: jnp.ndarray, patch_size: int) -> jnp.ndarray:
    """(B, H, W, C) → (B, H/P · W/P, P·P·C) via pure layout ops."""
    b, h, w, c = images.shape
    p = patch_size
    assert h % p == 0 and w % p == 0, (images.shape, p)
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, hp, wp, P, P, C)
    return x.reshape(b, (h // p) * (w // p), p * p * c)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def patch_embed(images: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray,
                patch_size: int,
                interpret: Optional[bool] = None) -> jnp.ndarray:
    """ViT patch embedding: (B,H,W,C) → (B, N_patches, D).

    ``w``: (P·P·C, D), ``b``: (D,).
    """
    patches = extract_patches(images, patch_size)
    bsz, n, k = patches.shape
    out = matmul_bias(patches.reshape(bsz * n, k), w, b,
                      interpret=interpret)
    return out.reshape(bsz, n, -1)


def _pe_fwd(images, w, b, patch_size, interpret):
    return patch_embed(images, w, b, patch_size, interpret), (images, w)


def _pe_bwd(patch_size, interpret, residuals, g):
    images, w = residuals
    bsz, n, d = g.shape
    patches = extract_patches(images, patch_size)
    k = patches.shape[-1]
    g2 = g.reshape(bsz * n, d).astype(jnp.float32)
    p2 = patches.reshape(bsz * n, k).astype(jnp.float32)
    dw = (p2.T @ g2).astype(w.dtype)
    db = jnp.sum(g2, axis=0).astype(w.dtype)
    dp = (g2 @ w.astype(jnp.float32).T).astype(images.dtype)
    # invert extract_patches layout
    p = patch_size
    h = images.shape[1]
    wd = images.shape[2]
    c = images.shape[3]
    dimg = dp.reshape(bsz, h // p, wd // p, p, p, c)
    dimg = dimg.transpose(0, 1, 3, 2, 4, 5).reshape(bsz, h, wd, c)
    return dimg, dw, db


patch_embed.defvjp(_pe_fwd, _pe_bwd)
