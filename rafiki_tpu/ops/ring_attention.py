"""Ring attention — sequence-parallel exact attention over an ICI ring.

Long-context support the TPU-first way (the reference has nothing here —
SURVEY.md §5.7 — but a TPU framework must scale sequence length past one
chip's HBM): the sequence axis is sharded over a mesh axis, every device
holds an L/P slice of Q, K, V, and K/V blocks rotate around the ring via
``jax.lax.ppermute`` while each device accumulates its queries' attention
over every block with the online-softmax (flash) recurrence. Peak memory
is O(L²/P²) per device for the blockwise scores — never the full L×L
matrix — and the K/V transfers ride neighbor-to-neighbor ICI links,
overlapping compute steps.

Built with ``shard_map`` + plain jnp math inside, so:
- XLA sees P program instances exchanging with ``ppermute`` — the
  collective schedule is the compiler's to overlap;
- the whole thing is differentiable for free (``ppermute`` has a
  transpose rule; the VJP runs the reverse ring), no custom backward;
- on one device it degrades to ordinary blockwise attention.

Causality uses global positions: device i's queries start at i·L/P, and
after s rotations its resident K/V block originated on device
(i − s) mod P, so the mask is exact across the ring — no recomputation
or padding tricks.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _local_block(q, k, v, q_off, k_off, sm_scale: float, causal: bool,
                 m, l, acc):
    """One online-softmax update of local queries against one K/V block.

    q: (b, h, sq, d); k/v: (b, h, sk, d); (m, l, acc): running max /
    normalizer / weighted-V accumulator, all f32.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        q_pos = q_off + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[2], k.shape[2]), 0)
        k_pos = k_off + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[2], k.shape[2]), 1)
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh, axis: str, sm_scale: Optional[float] = None,
                   causal: bool = False,
                   batch_axis: Optional[str] = None) -> jnp.ndarray:
    """Exact attention with Q/K/V sequence-sharded over ``mesh[axis]``.

    Inputs are (batch, heads, seq, head_dim) arrays whose ``seq`` dim is
    (or will be) sharded over the named mesh axis. On a multi-axis mesh
    pass ``batch_axis`` to keep the batch dim sharded over it (2-D
    dp × sp); any mesh axis named in neither is replicated over.
    Returns the attention output with the same sharding as the inputs
    were placed to. Differentiable end-to-end.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    scale = (sm_scale if sm_scale is not None
             else 1.0 / math.sqrt(q.shape[-1]))
    n_ring = mesh.shape[axis]
    seq_spec = P(batch_axis, None, axis, None)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec)
    def _ring(ql, kl, vl):
        # ql/kl/vl: the local (b, h, L/P, d) shards
        idx = jax.lax.axis_index(axis)
        sq = ql.shape[2]
        q_off = idx * sq

        m0 = jnp.full(ql.shape[:3], NEG_INF, jnp.float32)
        l0 = jnp.zeros(ql.shape[:3], jnp.float32)
        a0 = jnp.zeros(ql.shape, jnp.float32)

        def body(s, carry):
            kb, vb, m, l, acc = carry
            # block resident after s rotations originated on (idx - s)
            k_off = ((idx - s) % n_ring) * sq
            m, l, acc = _local_block(ql, kb, vb, q_off, k_off, scale,
                                     causal, m, l, acc)
            # rotate K/V one hop around the ring (neighbor ICI links)
            perm = [(j, (j + 1) % n_ring) for j in range(n_ring)]
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return kb, vb, m, l, acc

        # unrolled python loop: n_ring is static (mesh shape), and
        # unrolling lets XLA overlap each step's ppermute with the
        # next block's einsum
        carry = (kl, vl, m0, l0, a0)
        for s in range(n_ring):
            carry = body(s, carry)
        m, l, acc = carry[2:]
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        if causal:
            # fully-masked rows (none exist for causal self-attention,
            # but keep the zero convention of ops.attention)
            out = jnp.where((l > 0)[..., None], out, 0.0)
        return out.astype(ql.dtype)

    shard = NamedSharding(mesh, seq_spec)
    return _ring(jax.device_put(q, shard), jax.device_put(k, shard),
                 jax.device_put(v, shard))
