"""Ring attention — sequence-parallel exact attention over an ICI ring.

Long-context support the TPU-first way (the reference has nothing here —
SURVEY.md §5.7 — but a TPU framework must scale sequence length past one
chip's HBM): the sequence axis is sharded over a mesh axis, every device
holds an L/P slice of Q, K, V, and K/V blocks rotate around the ring via
``jax.lax.ppermute`` while each device accumulates its queries' attention
over every block. Each resident block runs through the Pallas flash
kernel (:mod:`rafiki_tpu.ops.attention` — the same streamed kernels
Ulysses uses), so peak per-device memory is O(block_q · block_k) kernel
tiles plus O(L/P · d) shards — never an (L/P)² score matrix (VERDICT r3
weak #4), let alone the full L×L one. K/V transfers ride
neighbor-to-neighbor ICI links, overlapping compute steps.

Per-block outputs are exact-combined with their log-sum-exp rows — the
standard blockwise-softmax identity: for blocks with row LSEs lse_s and
normalized outputs out_s, the total is Σ_s e^{lse_s − m}·out_s
normalized by Σ_s e^{lse_s − m}.

The backward is a hand-written custom VJP that runs the ring AGAIN in
reverse — residuals are only the local Q/K/V/out shards plus the
combined per-row LSE (O(L/P · d) per device). A naive autodiff of the
unrolled forward would instead retain every rotated K/V block as a
residual (P copies = the full global K/V per device), OOMing at exactly
the sequence lengths the ring exists to serve. In the backward pass the
K/V blocks rotate with TRAVELING dK/dV accumulators: each device adds
its queries' contribution to the resident block's gradient
(``flash_attention_block_bwd`` — global LSE makes per-block grads sum
exactly), and after P hops every accumulator is home with all
contributions.

Causality uses global positions at BLOCK granularity: device i's queries
start at i·L/P and after s rotations its resident K/V block originated
on device (i − s) mod P, so every step is one of exactly three cases —
the diagonal block (ordinary causal flash), a fully-visible past block
(non-causal flash), or a fully-masked future block, which ``lax.cond``
skips without issuing the kernel at all (half the ring on average, in
forward AND backward).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from rafiki_tpu.ops.attention import (NEG_INF, flash_attention_block_bwd,
                                      flash_attention_lse)
from rafiki_tpu.ops.common import shard_map_kernels


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh, axis: str, sm_scale: Optional[float] = None,
                   causal: bool = False,
                   batch_axis: Optional[str] = None,
                   head_axis: Optional[str] = None,
                   block_q: int = 128, block_k: int = 128,
                   interpret: Optional[bool] = None) -> jnp.ndarray:
    """Exact attention with Q/K/V sequence-sharded over ``mesh[axis]``.

    Inputs are (batch, heads, seq, head_dim) arrays whose ``seq`` dim is
    (or will be) sharded over the named mesh axis. On a multi-axis mesh
    pass ``batch_axis`` to keep the batch dim sharded over it (2-D
    dp × sp); any mesh axis named in neither is replicated over.
    ``block_q``/``block_k``/``interpret`` forward to the flash kernels
    (``interpret=None`` → Pallas on TPU, XLA twin elsewhere).
    Returns the attention output with the same sharding as the inputs
    were placed to. Differentiable end-to-end via the reverse ring.

    GQA-aware: ``k``/``v`` may carry ``kv_heads = heads / rep`` heads
    (query group g attends kv head ``g // rep`` — the ``jnp.repeat``
    convention). Only the SMALL ``kv_heads`` tensors rotate around the
    ring (and their dK/dV accumulators on the reverse ring — ``rep``×
    less neighbor-link traffic both ways); each resident block repeats
    locally before its kernel, and the block backward's dK/dV group-
    reduce back to ``kv_heads`` before accumulating. ``rep = 1``
    degenerates to plain multi-head exactly.

    Tensor-parallel composition: with ``head_axis`` set, the head dim
    is additionally sharded over that mesh axis and each TP shard runs
    its own independent ring over ``mesh[axis]`` (per-head attention
    never mixes heads, so the rings are embarrassingly parallel across
    head shards). Requires ``heads`` and ``kv_heads`` divisible by the
    head-axis size.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    scale = (sm_scale if sm_scale is not None
             else 1.0 / math.sqrt(q.shape[-1]))
    from rafiki_tpu.ops.common import gqa_repeat_factor

    h, h_kv = q.shape[1], k.shape[1]
    rep = gqa_repeat_factor(h, h_kv)

    def expand(t):
        # GQA: repeat a resident K/V block to q-head count — local
        # compute-side work; the ring never carries the copies
        return jnp.repeat(t, rep, axis=1) if rep > 1 else t

    def reduce_groups(t):
        # (b, h, l, d) block dK/dV → (b, h_kv, l, d): each kv head's
        # grad sums over its rep query heads (the VJP of expand).
        # Shapes here are LOCAL (head dim may be tp-sharded), so the
        # kv-head count derives from the block itself, not the global
        if rep == 1:
            return t
        bb, hh, ll, dd = t.shape
        return jnp.sum(t.reshape(bb, hh // rep, rep, ll, dd), axis=2)
    tp = mesh.shape[head_axis] if head_axis is not None else 1
    if h % tp or h_kv % tp:
        raise ValueError(
            f"ring_attention with head_axis needs heads ({h}) and "
            f"kv_heads ({h_kv}) divisible by mesh[{head_axis!r}] ({tp})")
    n_ring = mesh.shape[axis]
    seq_spec = P(batch_axis, head_axis, axis, None)
    lse_spec = P(batch_axis, head_axis, axis)
    ring_perm = [(j, (j + 1) % n_ring) for j in range(n_ring)]

    def rotate(*ts):
        return tuple(jax.lax.ppermute(t, axis, ring_perm) for t in ts)

    # ---- forward ring: combine per-block flash outputs via their LSEs
    @functools.partial(
        shard_map_kernels, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=(seq_spec, lse_spec))
    def _ring_fwd(ql, kl, vl):
        # ql: (b, h, L/P, d); kl/vl: (b, h_kv, L/P, d) — only the
        # small kv tensors ride the ring; blocks repeat locally
        idx = jax.lax.axis_index(axis)

        def skipped(ql):
            zeros = jnp.zeros_like(ql)
            # derive the sentinel from ql so both cond branches carry
            # the same varying-manual-axes type under shard_map (a bare
            # constant would be "unvarying" and fail to unify); XLA
            # folds this to a constant after SPMD partitioning
            lse = jnp.sum(zeros, axis=-1, dtype=jnp.float32) + NEG_INF
            return zeros, lse

        def combine(carry, out_s, lse_s):
            # online blockwise-softmax merge of a block's normalized
            # output; a skipped block's NEG_INF lse underflows to w=0
            m, l, acc = carry
            m_new = jnp.maximum(m, lse_s)
            alpha = jnp.exp(m - m_new)
            w = jnp.exp(lse_s - m_new)
            acc = acc * alpha[..., None] + \
                out_s.astype(jnp.float32) * w[..., None]
            return m_new, l * alpha + w, acc

        carry = (jnp.full(ql.shape[:3], NEG_INF, jnp.float32),
                 jnp.zeros(ql.shape[:3], jnp.float32),
                 jnp.zeros(ql.shape, jnp.float32))
        kb, vb = kl, vl
        # unrolled python loop: n_ring is static (mesh shape), and
        # unrolling lets XLA overlap each step's ppermute with the
        # next block's kernel
        for s in range(n_ring):
            if not causal:
                out_s, lse_s = flash_attention_lse(
                    ql, expand(kb), expand(vb), scale, False, block_q,
                    block_k, interpret)
            elif s == 0:
                # resident block IS the diagonal: plain causal flash
                # (q and k share their origin, no offset bookkeeping)
                out_s, lse_s = flash_attention_lse(
                    ql, expand(kb), expand(vb), scale, True, block_q,
                    block_k, interpret)
            else:
                # block originated on (idx - s) mod P: strictly past
                # blocks are fully visible, strictly future ones are
                # fully masked — skip the kernel entirely for those
                out_s, lse_s = jax.lax.cond(
                    (idx - s) % n_ring > idx,
                    lambda kb, vb: skipped(ql),
                    lambda kb, vb: flash_attention_lse(
                        ql, expand(kb), expand(vb), scale, False,
                        block_q, block_k, interpret),
                    kb, vb)
            carry = combine(carry, out_s, lse_s)
            if s + 1 < n_ring:
                # rotate K/V one hop around the ring (neighbor ICI)
                kb, vb = rotate(kb, vb)
        m, l, acc = carry
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        # combined log-normalizer per row: the backward residual that
        # lets each block's grads be computed independently
        lse_tot = m + jnp.log(jnp.maximum(l, 1e-30))
        return out.astype(ql.dtype), lse_tot

    # ---- backward ring: K/V rotate with traveling dK/dV accumulators
    @functools.partial(
        shard_map_kernels, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, seq_spec, lse_spec,
                  seq_spec),
        out_specs=(seq_spec, seq_spec, seq_spec))
    def _ring_bwd(ql, kl, vl, ol, lsel, gl):
        idx = jax.lax.axis_index(axis)

        def grads(kb, vb, diag=False):
            # diag=True: the resident block IS the causal diagonal
            dq_s, dk_s, dv_s = flash_attention_block_bwd(
                ql, expand(kb), expand(vb), ol, lsel, gl, scale,
                diag, block_q, block_k, interpret)
            return dq_s, reduce_groups(dk_s), reduce_groups(dv_s)

        def zero_grads(ql, kb):
            return (jnp.zeros(ql.shape, jnp.float32),
                    jnp.zeros(kb.shape, jnp.float32),
                    jnp.zeros(kb.shape, jnp.float32))

        dq = jnp.zeros(ql.shape, jnp.float32)
        kb, vb = kl, vl
        dkb = jnp.zeros(kl.shape, jnp.float32)
        dvb = jnp.zeros(vl.shape, jnp.float32)
        for s in range(n_ring):
            if not causal:
                dq_s, dk_s, dv_s = grads(kb, vb)
            elif s == 0:
                dq_s, dk_s, dv_s = grads(kb, vb, diag=True)
            else:
                dq_s, dk_s, dv_s = jax.lax.cond(
                    (idx - s) % n_ring > idx,
                    lambda kb, vb: zero_grads(ql, kb),
                    lambda kb, vb: grads(kb, vb),
                    kb, vb)
            dq = dq + dq_s
            dkb = dkb + dk_s
            dvb = dvb + dv_s
            # rotate grads WITH their block: after the full loop of P
            # hops each accumulator is back on its block's home device
            # carrying every device's contribution
            kb, vb, dkb, dvb = rotate(kb, vb, dkb, dvb)
        return (dq.astype(ql.dtype), dkb.astype(kl.dtype),
                dvb.astype(vl.dtype))

    @jax.custom_vjp
    def _ring(q, k, v):
        out, _ = _ring_fwd(q, k, v)
        return out

    def _fwd(q, k, v):
        out, lse = _ring_fwd(q, k, v)
        return out, (q, k, v, out, lse)

    def _bwd(res, g):
        q, k, v, out, lse = res
        return _ring_bwd(q, k, v, out, lse, g)

    _ring.defvjp(_fwd, _bwd)

    shard = NamedSharding(mesh, seq_spec)
    return _ring(jax.device_put(q, shard), jax.device_put(k, shard),
                 jax.device_put(v, shard))
