"""Model-developer harness: contract conformance + local tuning loop.

Parity target: the reference's ``test_model_class()`` and ``tune_model()``
dev utilities (SURVEY.md §3.5, §4) — the de-facto unit test every template
runs in its ``__main__`` block: construct with knobs → train → evaluate →
dump → load → predict round-trip, all in-process with no cluster.
"""

from __future__ import annotations

import random
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Type

import numpy as np

from .base import BaseModel, Params, TrainContext, serialize_model_class, \
    load_model_class
from .knob import Knobs, sample_knobs, validate_knobs, \
    validate_override_keys
from .log import ModelLogger


@dataclass
class TrialSummary:
    knobs: Knobs
    score: float
    logger: ModelLogger
    params: Optional[Params] = None


@dataclass
class TuneResult:
    best_knobs: Knobs
    best_score: float
    best_params: Params
    trials: List[TrialSummary] = field(default_factory=list)


def test_model_class(model_class: Type[BaseModel], task: str,
                     train_dataset_path: str, val_dataset_path: str,
                     queries: Sequence[Any], knobs: Optional[Knobs] = None,
                     seed: int = 0) -> List[Any]:
    """Run one full lifecycle through ``model_class`` and assert the contract.

    Returns the predictions on ``queries`` so callers can eyeball them.
    Raises AssertionError/ValueError on any contract violation.
    """
    assert issubclass(model_class, BaseModel), \
        "model class must subclass rafiki_tpu BaseModel"
    assert task in model_class.TASKS, \
        f"model does not declare task {task!r} (declares {model_class.TASKS})"

    knob_config = model_class.get_knob_config()
    if knobs is None:
        knobs = sample_knobs(knob_config, random.Random(seed))
    validate_knobs(knob_config, knobs)

    # transport round-trip: the class must survive source serialization
    clazz = load_model_class(serialize_model_class(model_class),
                             model_class.__name__)

    model = clazz(**knobs)
    ctx = TrainContext(logger=ModelLogger())
    model.train(train_dataset_path, ctx)
    score = model.evaluate(val_dataset_path)
    assert isinstance(score, float), \
        f"evaluate() must return float, got {type(score)}"

    params = model.dump_parameters()
    assert params is not None, "dump_parameters() returned None"
    params = _round_trip_numpy(params)

    model2 = clazz(**knobs)
    model2.load_parameters(params)
    score2 = model2.evaluate(val_dataset_path)
    assert abs(score - score2) < 1e-3, (
        f"dump/load round-trip changed eval score: {score} -> {score2}")

    predictions = model2.predict(list(queries))
    assert len(predictions) == len(queries), \
        "predict() must return one prediction per query"
    model.destroy()
    model2.destroy()
    return predictions


test_model_class.__test__ = False  # it's a dev harness, not a pytest case


def tune_model(model_class: Type[BaseModel], train_dataset_path: str,
               val_dataset_path: str, total_trials: int = 10,
               advisor_type: str = "auto", seed: int = 0,
               keep_params: bool = True,
               profile_dir: Optional[str] = None,
               knob_overrides: Optional[Dict[str, Any]] = None,
               gang_size: int = 0) -> TuneResult:
    """Local single-process tuning loop (reference ``tune_model``): run the
    advisor's propose/feedback cycle in-process and return the best trial.

    ``profile_dir`` wraps each trial's train() in a ``jax.profiler`` trace
    written to ``profile_dir/local-<trial_no>/`` (SURVEY.md §5.1).

    ``knob_overrides`` pins knobs over every proposal — the dev-loop
    twin of ``TrainWorker.knob_overrides`` (job-level pins), so local
    runs can hold shape knobs fixed while the advisor searches the
    rest. Unknown keys fail fast, same as the admin API's job-level
    validation.

    ``gang_size >= 1`` routes through the gang-compiled tuning engine
    (``rafiki_tpu/tuning``): K trials train as K lanes of one vmapped
    jit step — small-zoo templates only (those with ``make_gang_spec``;
    others fall back to this sequential loop with a warning)."""
    from ..advisor import make_advisor, TrialResult

    knob_config = model_class.get_knob_config()
    validate_override_keys(knob_config, knob_overrides,
                           context="knob_overrides")
    advisor = make_advisor(knob_config, advisor_type,
                           total_trials=total_trials, seed=seed)

    if gang_size >= 1:
        from ..tuning import supports_gang

        if supports_gang(model_class):
            blockers_fn = getattr(model_class, "gang_blockers", None)
            if callable(blockers_fn) and knob_overrides:
                # a pinned knob can force every bucket onto the
                # sequential path; name the culprit up front instead of
                # letting the engine's per-bucket fallback look like a
                # silent slowdown (gang_blockers reads knobs via .get,
                # so probing with just the pins is well-defined)
                pinned = blockers_fn(dict(knob_overrides))
                if pinned:
                    warnings.warn(
                        f"{model_class.__name__} gang lanes blocked by "
                        "pinned knobs: " + "; ".join(pinned)
                        + " — affected trials fall back to sequential")
            return _tune_model_gang(model_class, advisor,
                                    train_dataset_path, val_dataset_path,
                                    gang_size, knob_overrides, keep_params)
        warnings.warn(
            f"{model_class.__name__} has no gang spec; "
            "tune_model(gang_size=...) falling back to sequential trials")

    trials: List[TrialSummary] = []
    params_by_trial: Dict[str, Params] = {}

    while True:
        proposal = advisor.propose()
        if not proposal.is_valid:
            break
        if knob_overrides:
            proposal.knobs = {**proposal.knobs, **knob_overrides}
        logger = ModelLogger()
        model = model_class(**proposal.knobs)
        shared = params_by_trial.get(proposal.warm_start_trial_id)
        trial_profile_dir = None
        if profile_dir:
            import os

            trial_profile_dir = os.path.join(profile_dir,
                                             f"local-{proposal.trial_no}")
            os.makedirs(trial_profile_dir, exist_ok=True)
        ctx = TrainContext(logger=logger, budget_scale=proposal.budget_scale,
                           shared_params=shared,
                           trial_id=f"local-{proposal.trial_no}",
                           profile_dir=trial_profile_dir)
        try:
            if trial_profile_dir:
                import jax

                with jax.profiler.trace(trial_profile_dir):
                    model.train(train_dataset_path, ctx)
            else:
                model.train(train_dataset_path, ctx)
            score = model.evaluate(val_dataset_path)
        except Exception as e:
            # reference semantics: an errored trial is dropped and the
            # budget moves on (SURVEY.md §5.3)
            warnings.warn(f"trial {proposal.trial_no} errored: {e!r}")
            advisor.trial_errored(proposal.trial_no)
            model.destroy()
            continue
        params = _round_trip_numpy(model.dump_parameters())
        trial_id = f"local-{proposal.trial_no}"
        if keep_params:
            params_by_trial[trial_id] = params
        advisor.feedback(TrialResult(
            trial_no=proposal.trial_no, knobs=proposal.knobs, score=score,
            trial_id=trial_id, budget_scale=proposal.budget_scale,
            meta=proposal.meta))
        trials.append(TrialSummary(knobs=proposal.knobs, score=score,
                                   logger=logger,
                                   params=params if keep_params else None))
        model.destroy()

    best = advisor.best_effort
    if best is None:
        raise RuntimeError("no successful trial")
    return TuneResult(best_knobs=best.knobs, best_score=best.score,
                      best_params=params_by_trial.get(best.trial_id, {}),
                      trials=trials)


def _tune_model_gang(model_class: Type[BaseModel], advisor: Any,
                     train_dataset_path: str, val_dataset_path: str,
                     gang_size: int,
                     knob_overrides: Optional[Dict[str, Any]],
                     keep_params: bool) -> TuneResult:
    """Gang-engine twin of the sequential loop: same advisor cycle, K
    lanes per compiled step, one TrialSummary per lane-trial."""
    from ..tuning import GangEngine

    blobs: Dict[str, Params] = {}

    def on_result(result, blob) -> None:
        if keep_params:
            blobs[result.trial_id] = blob

    engine = GangEngine(model_class, advisor, train_dataset_path,
                        val_dataset_path, gang_size=gang_size, mode="gang",
                        knob_overrides=knob_overrides,
                        keep_blobs=True, on_result=on_result)
    results = engine.run()
    trials = [TrialSummary(knobs=r.knobs, score=r.score,
                           logger=ModelLogger(),
                           params=blobs.get(r.trial_id))
              for r in results]
    best = advisor.best_effort
    if best is None:
        raise RuntimeError("no successful trial")
    return TuneResult(best_knobs=best.knobs, best_score=best.score,
                      best_params=blobs.get(best.trial_id, {}),
                      trials=trials)


def _round_trip_numpy(params: Params) -> Params:
    """Force params through host numpy, as the ParamStore would."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, params)
