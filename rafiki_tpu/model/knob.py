"""Hyperparameter knob system — the per-model search-space language.

Parity target: the reference's knob classes (FixedKnob / CategoricalKnob /
IntegerKnob / FloatKnob / PolicyKnob) described in SURVEY.md §2 ("Model
contract"). Knobs are declarative: a model's ``get_knob_config()`` returns
``{name: knob}``; advisors sample/optimize over that space; a concrete
assignment (a "proposal") is just ``{name: value}``.

Design notes (TPU-first):
- Knobs carry a stable JSON wire form so the Advisor service and the
  MetaStore can exchange knob configs across processes without pickling.
- ``to_unit``/``from_unit`` map values into [0,1]^d for Bayesian/GP
  optimization (log-scaling handled per-knob), so advisor algorithms never
  special-case knob types.
- ``shape_relevant`` marks knobs that change traced array shapes (e.g.
  hidden width). The trial scheduler uses it to bucket proposals by XLA
  compile signature and amortize compilation across trials (SURVEY.md §7
  "Compile-time amortization in search").
- ``traceable`` marks knobs whose value can be threaded into a compiled
  train step as a traced array operand (learning rate, dropout, weight
  decay, momentum, ...). The gang-compiled tuning engine
  (``rafiki_tpu/tuning``) runs K configurations that differ only in
  traceable knobs as K lanes of ONE ``jax.vmap``-ed jit step — no
  per-trial recompile. Non-traceable knobs define the *static bucket*
  (:func:`static_signature`): one compile per bucket, not per trial.
  ``traceable`` and ``shape_relevant`` are mutually exclusive — a knob
  that changes array shapes can never be a traced operand.
"""

from __future__ import annotations

import math
import random as _random
from typing import (Any, Dict, Iterable, List, Mapping, Optional, Sequence,
                    Union)

KnobValue = Union[int, float, str, bool]


class BaseKnob:
    """A single hyperparameter's declared domain."""

    #: subclasses set this; used for JSON round-trip dispatch
    kind: str = "base"

    def __init__(self, shape_relevant: bool = False,
                 traceable: bool = False) -> None:
        if shape_relevant and traceable:
            raise ValueError(
                "a knob cannot be both shape_relevant and traceable: "
                "shape changes force a recompile, traced operands must not")
        self.shape_relevant = shape_relevant
        self.traceable = traceable

    # ---- sampling / optimization interface ----
    def sample(self, rng: _random.Random) -> KnobValue:
        raise NotImplementedError

    def to_unit(self, value: KnobValue) -> float:
        """Map a concrete value into [0, 1] for continuous optimizers."""
        raise NotImplementedError

    def from_unit(self, u: float) -> KnobValue:
        """Inverse of :meth:`to_unit` (clipping u into [0, 1])."""
        raise NotImplementedError

    @property
    def is_constant(self) -> bool:
        return False

    def validate(self, value: KnobValue) -> bool:
        raise NotImplementedError

    # ---- wire format ----
    def to_json(self) -> Dict[str, Any]:
        raise NotImplementedError

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "BaseKnob":
        kind = d["kind"]
        cls = _KNOB_KINDS.get(kind)
        if cls is None:
            raise ValueError(f"unknown knob kind: {kind!r}")
        return cls._from_json(d)

    @classmethod
    def _from_json(cls, d: Dict[str, Any]) -> "BaseKnob":
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.to_json()})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BaseKnob) and self.to_json() == other.to_json()

    def __hash__(self) -> int:
        return hash(repr(sorted(self.to_json().items(), key=str)))


class FixedKnob(BaseKnob):
    """A knob pinned to one value (not searched)."""

    kind = "fixed"

    def __init__(self, value: KnobValue, shape_relevant: bool = False,
                 traceable: bool = False) -> None:
        super().__init__(shape_relevant, traceable)
        self.value = value

    def sample(self, rng: _random.Random) -> KnobValue:
        return self.value

    def to_unit(self, value: KnobValue) -> float:
        return 0.0

    def from_unit(self, u: float) -> KnobValue:
        return self.value

    @property
    def is_constant(self) -> bool:
        return True

    def validate(self, value: KnobValue) -> bool:
        return value == self.value

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.kind, "value": self.value,
                "shape_relevant": self.shape_relevant,
                "traceable": self.traceable}

    @classmethod
    def _from_json(cls, d: Dict[str, Any]) -> "FixedKnob":
        return cls(d["value"], d.get("shape_relevant", False),
                   d.get("traceable", False))


class CategoricalKnob(BaseKnob):
    """A knob over an explicit finite set of values."""

    kind = "categorical"

    def __init__(self, values: Sequence[KnobValue],
                 shape_relevant: bool = False,
                 traceable: bool = False) -> None:
        super().__init__(shape_relevant, traceable)
        if not values:
            raise ValueError("CategoricalKnob requires at least one value")
        self.values = list(values)

    def sample(self, rng: _random.Random) -> KnobValue:
        return rng.choice(self.values)

    def to_unit(self, value: KnobValue) -> float:
        idx = self.values.index(value)
        if len(self.values) == 1:
            return 0.0
        return idx / (len(self.values) - 1)

    def from_unit(self, u: float) -> KnobValue:
        u = min(max(u, 0.0), 1.0)
        idx = min(int(round(u * (len(self.values) - 1))), len(self.values) - 1)
        return self.values[idx]

    @property
    def is_constant(self) -> bool:
        return len(self.values) == 1

    def validate(self, value: KnobValue) -> bool:
        return value in self.values

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.kind, "values": self.values,
                "shape_relevant": self.shape_relevant,
                "traceable": self.traceable}

    @classmethod
    def _from_json(cls, d: Dict[str, Any]) -> "CategoricalKnob":
        return cls(d["values"], d.get("shape_relevant", False),
                   d.get("traceable", False))


class IntegerKnob(BaseKnob):
    """An integer range [value_min, value_max], optionally log-scaled."""

    kind = "integer"

    def __init__(self, value_min: int, value_max: int, is_exp: bool = False,
                 shape_relevant: bool = False,
                 traceable: bool = False) -> None:
        super().__init__(shape_relevant, traceable)
        if value_min > value_max:
            raise ValueError("value_min must be <= value_max")
        if is_exp and value_min <= 0:
            raise ValueError("log-scaled IntegerKnob requires value_min > 0")
        self.value_min = int(value_min)
        self.value_max = int(value_max)
        self.is_exp = is_exp

    def sample(self, rng: _random.Random) -> int:
        return self.from_unit(rng.random())

    def to_unit(self, value: KnobValue) -> float:
        v = float(value)
        if self.value_min == self.value_max:
            return 0.0
        if self.is_exp:
            return (math.log(v) - math.log(self.value_min)) / (
                math.log(self.value_max) - math.log(self.value_min))
        return (v - self.value_min) / (self.value_max - self.value_min)

    def from_unit(self, u: float) -> int:
        u = min(max(u, 0.0), 1.0)
        if self.is_exp:
            v = math.exp(math.log(self.value_min) + u * (
                math.log(self.value_max) - math.log(self.value_min)))
        else:
            v = self.value_min + u * (self.value_max - self.value_min)
        return int(min(max(round(v), self.value_min), self.value_max))

    @property
    def is_constant(self) -> bool:
        return self.value_min == self.value_max

    def validate(self, value: KnobValue) -> bool:
        return (isinstance(value, int) and not isinstance(value, bool)
                and self.value_min <= value <= self.value_max)

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.kind, "value_min": self.value_min,
                "value_max": self.value_max, "is_exp": self.is_exp,
                "shape_relevant": self.shape_relevant,
                "traceable": self.traceable}

    @classmethod
    def _from_json(cls, d: Dict[str, Any]) -> "IntegerKnob":
        return cls(d["value_min"], d["value_max"], d.get("is_exp", False),
                   d.get("shape_relevant", False),
                   d.get("traceable", False))


class FloatKnob(BaseKnob):
    """A float range [value_min, value_max], optionally log-scaled."""

    kind = "float"

    def __init__(self, value_min: float, value_max: float,
                 is_exp: bool = False, shape_relevant: bool = False,
                 traceable: bool = False) -> None:
        super().__init__(shape_relevant, traceable)
        if value_min > value_max:
            raise ValueError("value_min must be <= value_max")
        if is_exp and value_min <= 0:
            raise ValueError("log-scaled FloatKnob requires value_min > 0")
        self.value_min = float(value_min)
        self.value_max = float(value_max)
        self.is_exp = is_exp

    def sample(self, rng: _random.Random) -> float:
        return self.from_unit(rng.random())

    def to_unit(self, value: KnobValue) -> float:
        v = float(value)
        if self.value_min == self.value_max:
            return 0.0
        if self.is_exp:
            return (math.log(v) - math.log(self.value_min)) / (
                math.log(self.value_max) - math.log(self.value_min))
        return (v - self.value_min) / (self.value_max - self.value_min)

    def from_unit(self, u: float) -> float:
        u = min(max(u, 0.0), 1.0)
        if self.is_exp:
            return math.exp(math.log(self.value_min) + u * (
                math.log(self.value_max) - math.log(self.value_min)))
        return self.value_min + u * (self.value_max - self.value_min)

    @property
    def is_constant(self) -> bool:
        return self.value_min == self.value_max

    def validate(self, value: KnobValue) -> bool:
        return (isinstance(value, (int, float)) and not isinstance(value, bool)
                and self.value_min <= float(value) <= self.value_max)

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.kind, "value_min": self.value_min,
                "value_max": self.value_max, "is_exp": self.is_exp,
                "shape_relevant": self.shape_relevant,
                "traceable": self.traceable}

    @classmethod
    def _from_json(cls, d: Dict[str, Any]) -> "FloatKnob":
        return cls(d["value_min"], d["value_max"], d.get("is_exp", False),
                   d.get("shape_relevant", False),
                   d.get("traceable", False))


class PolicyKnob(BaseKnob):
    """Declares that the model implements a *policy* the system may toggle.

    Mirrors the reference's PolicyKnob: e.g. ``PolicyKnob('EARLY_STOP')``
    says the model honors early stopping when the advisor asks for it. The
    advisor/worker decide the boolean; the model reads it like any knob.
    """

    kind = "policy"

    KNOWN_POLICIES = (
        "EARLY_STOP",          # train fewer epochs when advisor probes cheaply
        "SHARE_PARAMS",        # accept warm-start params from ParamStore
        "QUICK_TRAIN",         # budget-scaled training (BOHB rungs)
        "SKIP_TRAIN",          # evaluate loaded params only
        "QUICK_EVAL",          # subsample eval set
        "DOWNSCALE",           # reduced model for low rungs
        "ADAPTERS_ONLY",       # strict-LoRA training (multi-adapter serving)
    )

    def __init__(self, policy: str, shape_relevant: bool = False,
                 traceable: bool = False) -> None:
        super().__init__(shape_relevant, traceable)
        self.policy = policy

    def sample(self, rng: _random.Random) -> bool:
        return False  # policies default off; advisors enable deliberately

    def to_unit(self, value: KnobValue) -> float:
        return 1.0 if value else 0.0

    def from_unit(self, u: float) -> bool:
        return u >= 0.5

    def validate(self, value: KnobValue) -> bool:
        return isinstance(value, bool)

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.kind, "policy": self.policy,
                "shape_relevant": self.shape_relevant,
                "traceable": self.traceable}

    @classmethod
    def _from_json(cls, d: Dict[str, Any]) -> "PolicyKnob":
        return cls(d["policy"], d.get("shape_relevant", False),
                   d.get("traceable", False))


_KNOB_KINDS = {c.kind: c for c in
               (FixedKnob, CategoricalKnob, IntegerKnob, FloatKnob, PolicyKnob)}

KnobConfig = Dict[str, BaseKnob]
Knobs = Dict[str, KnobValue]


# ---------------------------------------------------------------------------
# KnobConfig helpers (module-level; a knob config is a plain dict)
# ---------------------------------------------------------------------------

def knob_config_to_json(knob_config: KnobConfig) -> Dict[str, Any]:
    return {name: knob.to_json() for name, knob in knob_config.items()}

def knob_config_from_json(d: Dict[str, Any]) -> KnobConfig:
    return {name: BaseKnob.from_json(kd) for name, kd in d.items()}

def sample_knobs(knob_config: KnobConfig,
                 rng: Optional[_random.Random] = None) -> Knobs:
    rng = rng or _random.Random()
    return {name: knob.sample(rng) for name, knob in knob_config.items()}

def validate_knobs(knob_config: KnobConfig, knobs: Knobs) -> None:
    """Raise ValueError if ``knobs`` is not a full, in-domain assignment."""
    missing = set(knob_config) - set(knobs)
    if missing:
        raise ValueError(f"missing knobs: {sorted(missing)}")
    extra = set(knobs) - set(knob_config)
    if extra:
        raise ValueError(f"unknown knobs: {sorted(extra)}")
    for name, knob in knob_config.items():
        if not knob.validate(knobs[name]):
            raise ValueError(
                f"knob {name!r}={knobs[name]!r} out of domain for {knob!r}")

def tunable_knobs(knob_config: KnobConfig) -> List[str]:
    """Names of non-constant, non-policy knobs, in sorted order.

    This is the optimizer-visible dimensionality; sorted so every process
    agrees on the unit-cube axis order without coordination.
    """
    return sorted(name for name, knob in knob_config.items()
                  if not knob.is_constant and not isinstance(knob, PolicyKnob))

def knobs_to_unit_vector(knob_config: KnobConfig, knobs: Knobs) -> List[float]:
    return [knob_config[name].to_unit(knobs[name])
            for name in tunable_knobs(knob_config)]

def knobs_from_unit_vector(knob_config: KnobConfig, vector: Sequence[float],
                           rng: Optional[_random.Random] = None) -> Knobs:
    """Expand a unit-cube point into a full assignment (constants filled in,
    policies defaulted off)."""
    names = tunable_knobs(knob_config)
    if len(vector) != len(names):
        raise ValueError(f"expected {len(names)} dims, got {len(vector)}")
    rng = rng or _random.Random()
    knobs: Knobs = {}
    for name, knob in knob_config.items():
        if name in names:
            knobs[name] = knob.from_unit(vector[names.index(name)])
        elif isinstance(knob, PolicyKnob):
            knobs[name] = False
        else:
            knobs[name] = knob.sample(rng)
    return knobs

def shape_signature(knob_config: KnobConfig, knobs: Knobs) -> str:
    """Stable key over shape-relevant knob values.

    Trials with equal signatures produce identically-shaped jaxprs, so the
    worker can reuse cached XLA executables across them.
    """
    items = sorted((n, knobs[n]) for n, k in knob_config.items()
                   if k.shape_relevant)
    return repr(items)


def traceable_knobs(knob_config: KnobConfig) -> List[str]:
    """Names of knobs declared ``traceable``, in sorted order.

    These are the per-lane traced operands of a gang-compiled train step;
    sorted so every process packs lane hyperparameter arrays in the same
    axis order without coordination."""
    return sorted(n for n, k in knob_config.items() if k.traceable)


def static_signature(knob_config: KnobConfig, knobs: Knobs) -> str:
    """Stable key over NON-traceable knob values — the compile bucket.

    Two proposals with equal static signatures differ only in traced
    operands, so they can run as lanes of the same vmapped executable:
    one compile per bucket, not per trial. A superset of
    :func:`shape_signature` — non-shape static knobs like an optimizer
    choice also fork the compiled program — EXCEPT policy knobs: those
    are system toggles handled outside the traced step by contract
    (budget scaling, warm-start gating), and BOHB flips them per rung,
    so keying on them would force a recompile at every rung boundary."""
    items = sorted((n, knobs.get(n)) for n, k in knob_config.items()
                   if not k.traceable and not isinstance(k, PolicyKnob))
    return repr(items)


def validate_override_keys(known: Iterable[str],
                           overrides: Optional[Mapping[str, Any]],
                           context: str = "knob_overrides") -> None:
    """Reject override keys that name no known knob.

    One validator for every override surface — the admin API
    (``ServicesManager`` job-level pins) and the dev loop
    (``tune_model(knob_overrides=)``) — so a typo'd key fails fast
    everywhere instead of silently letting the advisor search the
    dimension the user believes is pinned."""
    if not overrides:
        return
    known = set(known)
    unknown = set(overrides) - known
    if unknown:
        raise ValueError(
            f"{context} {sorted(unknown)} match no knob "
            f"(known: {sorted(known)})")
