"""The model-template plugin contract — the system's central interface.

Parity target: the reference's ``BaseModel`` (SURVEY.md §2 "Model contract"):
``get_knob_config() / train / evaluate / predict / dump_parameters /
load_parameters`` plus the dev-time conformance harness. Every template in
the zoo implements this; the train worker, inference worker, predictor and
advisor all speak only this interface.

TPU-first deltas from the reference:
- Parameters are **JAX pytrees** (dicts of numpy/jax arrays), not opaque
  byte blobs; serialization to bytes lives in the ParamStore layer
  (flax.serialization msgpack), keeping models pure.
- ``train`` receives an optional :class:`TrainContext` carrying the trial's
  device sub-mesh, budget scale (for BOHB rungs), and a metric logger —
  instead of the reference's implicit globals.
- Model classes travel between services as *source code + class name*
  (see :func:`serialize_model_class` / :func:`load_model_class`), not
  pickles: safer, diffable, and survives process/interpreter boundaries.
"""

from __future__ import annotations

import abc
import hashlib
import importlib.util
import sys
import tempfile
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Type

from ..constants import TaskType
from .knob import KnobConfig, Knobs, validate_knobs
from .log import ModelLogger

Params = Dict[str, Any]  # a JAX pytree of arrays (or None)


@dataclass
class TrainContext:
    """Everything the system injects into a trial's ``train`` call."""

    #: devices this trial owns (a contiguous ICI sub-mesh); None = all local
    devices: Optional[List[Any]] = None
    #: fraction of the full training budget to spend (BOHB rung scaling)
    budget_scale: float = 1.0
    #: warm-start parameters from the ParamStore (SHARE_PARAMS policy)
    shared_params: Optional[Params] = None
    #: per-trial structured metric logger
    logger: ModelLogger = field(default_factory=ModelLogger)
    #: trial identity, for checkpoints/log correlation
    trial_id: Optional[str] = None
    #: hook the worker uses to let BOHB pause/stop a trial between epochs;
    #: called with (epoch, score) -> True to continue, False to stop early
    should_continue: Optional[Any] = None
    #: when set, the worker wraps train() in a ``jax.profiler`` trace and
    #: writes it here (SURVEY.md §5.1 — a per-trial capability the
    #: reference lacks); templates may also drop their own artifacts here
    profile_dir: Optional[str] = None
    #: preemption safety (SURVEY.md §5.3): when set, templates call
    #: ``ctx.checkpoint(self.dump_parameters, frac_done=(e+1)/epochs)`` at
    #: epoch boundaries with a ZERO-ARG blob factory — the worker
    #: throttles by wall clock and only then materializes the blob (host
    #: copy) and saves it. ``frac_done`` records training progress so a
    #: resumed trial trains only the REMAINING budget, keeping scores
    #: comparable to un-preempted trials. Big-model templates may also
    #: pass ``tree=<live sharded pytree>``: sharded-capable stores then
    #: save per-shard and asynchronously (SURVEY §5.4) instead of
    #: calling the whole-tree blob factory, and the later warm start
    #: arrives as a lazy handle with ``.restore(template)`` in
    #: ``shared_params`` instead of a host tree.
    checkpoint: Optional[Any] = None


class BaseModel(abc.ABC):
    """Contract every model template implements.

    Lifecycle driven by the train worker (SURVEY.md §3.1):
    ``Model(**knobs)`` → ``train(dataset, ctx)`` → ``evaluate(dataset)`` →
    ``dump_parameters()`` → (ParamStore) — and by the inference worker:
    ``Model(**best_knobs)`` → ``load_parameters(params)`` →
    ``predict(queries)``.
    """

    #: tasks this template can serve; checked by Admin at model registration
    TASKS: Sequence[str] = (TaskType.IMAGE_CLASSIFICATION,)

    def __init__(self, **knobs: Any) -> None:
        self.knobs: Knobs = dict(knobs)

    # ---- search space ----
    @staticmethod
    @abc.abstractmethod
    def get_knob_config() -> KnobConfig:
        """Declare the hyperparameter search space."""

    # ---- training-side ----
    @abc.abstractmethod
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        """Train on the dataset at ``dataset_path`` (format is task-specific)."""

    @abc.abstractmethod
    def evaluate(self, dataset_path: str) -> float:
        """Return a scalar score (higher is better) on a held-out dataset."""

    # ---- serving-side ----
    @abc.abstractmethod
    def predict(self, queries: Sequence[Any]) -> List[Any]:
        """Predict a batch of queries. For classification tasks, return a
        list of class-probability vectors (lists of float) so the Predictor
        can ensemble across models by probability averaging."""

    # ---- checkpointing ----
    @abc.abstractmethod
    def dump_parameters(self) -> Params:
        """Return trained parameters as a JAX pytree (numpy-convertible)."""

    @abc.abstractmethod
    def load_parameters(self, params: Params) -> None:
        """Restore parameters produced by :meth:`dump_parameters`."""

    # ---- optional hooks ----
    def destroy(self) -> None:
        """Release device memory/resources. Default: no-op."""

    def warmup(self) -> None:
        """Pre-compile the serving path (called by the inference worker
        at boot, AFTER load_parameters). Without it the first user
        request pays the XLA compile — seconds to minutes on TPU.
        Default: no-op; templates run one dummy query through their
        cached jitted forward."""

    @classmethod
    def validate_knobs(cls, knobs: Knobs) -> None:
        validate_knobs(cls.get_knob_config(), knobs)


# ---------------------------------------------------------------------------
# Model class transport: source + class name (replaces reference's pickling)
# ---------------------------------------------------------------------------

def serialize_model_class(model_class: Type[BaseModel]) -> bytes:
    """Capture a model class as the UTF-8 source of its defining module."""
    import inspect

    src = inspect.getsource(sys.modules[model_class.__module__])
    return src.encode("utf-8")


_MODULE_DIR: Optional[Path] = None


def _module_dir() -> Path:
    global _MODULE_DIR
    if _MODULE_DIR is None:
        _MODULE_DIR = Path(tempfile.mkdtemp(prefix="rafiki_tpu_models_"))
    return _MODULE_DIR


def load_model_class(model_bytes: bytes, class_name: str,
                     module_hint: str = "rafiki_model") -> Type[BaseModel]:
    """Materialize a model class from serialized module source.

    The module is written to a temp file and imported under a
    content-hashed name so repeated loads of the same bytes share a module
    and different models never collide.
    """
    digest = hashlib.sha256(model_bytes).hexdigest()[:16]
    mod_name = f"_rafiki_tpu_model_{module_hint}_{digest}"
    if mod_name in sys.modules:
        mod = sys.modules[mod_name]
    else:
        # per-process private dir: avoids races/symlink games in a shared /tmp
        tmpdir = _module_dir()
        path = tmpdir / f"{mod_name}.py"
        path.write_bytes(model_bytes)
        spec = importlib.util.spec_from_file_location(mod_name, path)
        assert spec is not None and spec.loader is not None
        mod = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = mod
        try:
            spec.loader.exec_module(mod)
        except Exception:
            del sys.modules[mod_name]
            raise
    clazz = getattr(mod, class_name, None)
    if clazz is None or not (isinstance(clazz, type)
                             and issubclass(clazz, BaseModel)):
        raise ValueError(
            f"{class_name!r} is not a BaseModel subclass in uploaded module")
    return clazz
