"""Per-trial structured metric logging.

Parity target: the reference's model ``logger`` / ``utils.logger`` whose
records land in the DB and render as loss/accuracy curves (SURVEY.md §5.1).
A :class:`ModelLogger` buffers records in-process; the train worker attaches
a sink that forwards them to the MetaStore, and the dev harness just reads
the buffer back.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class LogRecord:
    time: float
    kind: str          # "message" | "values" | "plot_def"
    data: Dict[str, Any]


@dataclass
class ModelLogger:
    """Collects messages, metric values, and plot definitions for one trial."""

    records: List[LogRecord] = field(default_factory=list)
    sink: Optional[Callable[[LogRecord], None]] = None

    def _emit(self, kind: str, data: Dict[str, Any]) -> None:
        rec = LogRecord(time=time.time(), kind=kind, data=data)
        self.records.append(rec)
        if self.sink is not None:
            self.sink(rec)

    def log(self, message: str = "", **values: Any) -> None:
        """Log a free-form message and/or named metric values
        (e.g. ``logger.log(epoch=3, loss=0.12, acc=0.95)``)."""
        if message:
            self._emit("message", {"message": message})
        if values:
            self._emit("values", {k: _to_plain(v) for k, v in values.items()})

    def log_loss(self, loss: float, epoch: Optional[int] = None) -> None:
        values: Dict[str, Any] = {"loss": _to_plain(loss)}
        if epoch is not None:
            values["epoch"] = epoch
        self._emit("values", values)

    def define_plot(self, title: str, metrics: List[str],
                    x_axis: str = "epoch") -> None:
        """Declare a plot over logged metric names (rendered by the UI)."""
        self._emit("plot_def",
                   {"title": title, "metrics": metrics, "x_axis": x_axis})

    # ---- read-back helpers (dev harness / tests) ----
    def get_values(self, name: str) -> List[Any]:
        return [r.data[name] for r in self.records
                if r.kind == "values" and name in r.data]

    def get_messages(self) -> List[str]:
        return [r.data["message"] for r in self.records if r.kind == "message"]


def _to_plain(v: Any) -> Any:
    """Coerce jax/numpy scalars to plain Python for JSON/SQLite transport."""
    try:
        import numpy as np

        if isinstance(v, np.generic):
            return v.item()
        if hasattr(v, "item") and getattr(v, "ndim", None) == 0:
            return v.item()
    except Exception:  # rafiki: noqa[silent-except] — best-effort
        pass           # scalar coercion; the raw value is returned
    return v
