"""Shared training epoch driver for the zoo templates.

Every template's epoch loop wants the same TPU-side plumbing:
double-buffered host→HBM prefetch (transfer of batch k+1 overlaps the
compiled step on batch k), device-scalar loss collection with a bounded
run-ahead sync (no per-step ``float()`` serialization, no unbounded
dispatch queue holding every in-flight batch in HBM), and a mean loss
materialized once at epoch end. One implementation here instead of a
per-template copy.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import (Any, Callable, Dict, Iterator, Optional, Sequence,
                    Tuple)

import numpy as np

from ..data.loader import prefetch_to_device

#: steps between jax.block_until_ready syncs: full overlap, bounded
#: number of in-flight batches resident in HBM
SYNC_EVERY = 8


def train_epoch(step: Callable[[Any, dict], Tuple[Any, Any]],
                state: Any, host_batches: Iterator[dict],
                sharding: Optional[Any] = None,
                sync_every: int = SYNC_EVERY) -> Tuple[Any, float]:
    """Thread ``state`` through ``step(state, batch) -> (state, loss)``
    over one epoch of batches.

    With ``sharding`` the host batches are prefetched to device under it
    (each dict leaf placed with the same NamedSharding). ``step`` is the
    template's adapter around its jitted (usually donated) train_step.
    Returns (final state, mean loss as float).
    """
    import jax

    batches = (prefetch_to_device(host_batches, sharding=sharding)
               if sharding is not None else host_batches)
    losses = []
    for batch in batches:
        state, loss = step(state, batch)
        losses.append(loss)
        if sync_every and len(losses) % sync_every == 0:
            jax.block_until_ready(loss)
    if not losses:
        return state, float("nan")
    return state, float(np.mean([float(l) for l in losses]))


@dataclass
class GangSpec:
    """A template's *functional* training recipe — the contract the
    gang-compiled tuning engine (``rafiki_tpu/tuning``) drives.

    The ordinary :meth:`BaseModel.train` is imperative: it owns its epoch
    loop and bakes every knob into Python. A gang spec factors the same
    computation into pure functions over an explicit per-lane ``state``
    pytree, with the template's *traceable* knobs arriving as a dict of
    traced scalars (``hp``). The engine vmaps these functions over K
    lanes (lane = trial) so K configurations train inside ONE compiled
    step; all non-traceable knobs were already burned in when the
    template built the spec (one spec per static bucket —
    :func:`rafiki_tpu.model.knob.static_signature`).

    Templates opt in via ``make_gang_spec(knobs, train_path, val_path)``
    (a classmethod returning one of these) plus ``gang_epochs(knobs,
    budget_scale)``; the engine falls back to per-trial sequential
    execution for templates that don't.

    Semantics contract (checked by tier-1 equivalence tests): driving a
    1-lane gang through ``init_lane``/``train_step``/``eval_lane`` must
    reproduce the template's sequential ``train()``/``evaluate()``
    bit-for-bit on the same dataset and knob assignment.
    """

    #: traceable knob names, in the axis order the engine packs per-lane
    #: hp arrays (use ``traceable_knobs(get_knob_config())``)
    hp_names: Sequence[str]
    #: ``(rng, hp) -> state`` — build ONE lane's state (params + opt);
    #: must not depend on hp for pytree STRUCTURE (values only)
    init_lane: Callable[[Any, Dict[str, Any]], Any]
    #: ``(state, hp, batch) -> (state, loss)`` — pure; vmapped over
    #: state, hp AND batch (in_axes=(0, 0, 0)) and jitted with the
    #: state donated. The batch axis is per-lane because each lane
    #: follows its OWN epoch schedule (a refilled lane restarts at
    #: epoch 0), so lane i's batch at any step is exactly what its
    #: sequential twin would see — do not assume lanes share data
    train_step: Callable[[Any, Dict[str, Any], Dict[str, Any]],
                         Tuple[Any, Any]]
    #: ``(epoch) -> iterator of host batch dicts`` (static shapes; the
    #: same batches the template's sequential loop sees at that epoch —
    #: the engine stacks one batch per lane from per-lane iterators)
    epoch_batches: Callable[[int], Iterator[Dict[str, np.ndarray]]]
    #: scoring contract per ``score_kind``: "accuracy" → ``(state, hp,
    #: xb) -> predicted class ids [B]`` (engine computes masked accuracy
    #: over ``eval_batches``); "lm" → ``(state, hp, batch) ->
    #: (loss_sum, valid_count)`` scalars (engine accumulates and scores
    #: ``exp(-sum/count)``, the LM template's inverse perplexity)
    eval_lane: Callable[[Any, Dict[str, Any], Any], Any]
    #: ``() -> iterator of host eval batches`` ("accuracy": ``{"x", "y",
    #: "mask"}``; "lm": whatever ``eval_lane`` consumes — the SAME
    #: padded batch stream the template's ``evaluate()`` walks)
    eval_batches: Callable[[], Iterator[Dict[str, np.ndarray]]]
    #: ``(lane_state, hp) -> blob`` — a ``dump_parameters()``-shaped
    #: blob for the ParamStore / TuneResult (host numpy). ``hp`` holds
    #: the lane's traceable knob values as floats so value-folding
    #: exports (e.g. LoRA rank-scale folded into ``lora_b``) see them
    export_blob: Callable[[Any, Dict[str, float]], Dict[str, Any]]
    #: ``(fresh_state, parent_blob) -> state`` — warm-start a lane from a
    #: completed trial's blob (params from the blob, optimizer fresh —
    #: exactly what the sequential warm-start path does)
    warm_lane: Callable[[Any, Dict[str, Any]], Any]
    #: name of the template's SHARE_PARAMS policy knob, if any: the
    #: engine only applies a proposal's warm start when this knob is
    #: truthy in its assignment (mirrors the sequential gate)
    share_params_knob: Optional[str] = None
    #: how the engine scores lanes over ``eval_batches``: "accuracy"
    #: (classification zoo) or "lm" (inverse perplexity — see
    #: ``eval_lane``)
    score_kind: str = "accuracy"
    #: tokens one real training sample contributes per step (LM
    #: templates: max_len). Feeds the engine's per-lane tokens/s
    #: gauges; 0 disables token accounting
    tokens_per_sample: int = 0
    #: parameter count of ONE lane's full forward (broadcast base +
    #: adapters) — the engine's per-lane est-MFU gauge uses the
    #: 6·N·tokens/s approximation; 0 disables the gauge
    lane_param_count: int = 0
    #: XLA compiler options for the gang's jitted step (e.g. the
    #: ``overlap_collectives`` schedule knob —
    #: :func:`rafiki_tpu.parallel.sharding.overlap_compiler_options`);
    #: None compiles with defaults. Static by construction: the knob is
    #: non-traceable, so each option set is its own compile bucket
    compiler_options: Optional[Dict[str, Any]] = None
    #: optional ``(lane_state, hp, batch) -> eval terms`` running ONE
    #: lane on the template's *sequential* ``evaluate()`` graph (e.g.
    #: value-folding knobs applied eagerly, then the same jitted
    #: forward ``evaluate()`` compiles). When set, the engine scores
    #: lanes through this instead of vmapping ``eval_lane`` — scoring
    #: is where the bit-exactness contract is settled, and a vmapped
    #: (or differently fused) eval graph can drift in the low bits on
    #: large forwards even though the math is identical
    eval_seq: Optional[Callable[[Any, Dict[str, Any], Any], Any]] = None
