"""Shared training epoch driver for the zoo templates.

Every template's epoch loop wants the same TPU-side plumbing:
double-buffered host→HBM prefetch (transfer of batch k+1 overlaps the
compiled step on batch k), device-scalar loss collection with a bounded
run-ahead sync (no per-step ``float()`` serialization, no unbounded
dispatch queue holding every in-flight batch in HBM), and a mean loss
materialized once at epoch end. One implementation here instead of a
per-template copy.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional, Tuple

import numpy as np

from ..data.loader import prefetch_to_device

#: steps between jax.block_until_ready syncs: full overlap, bounded
#: number of in-flight batches resident in HBM
SYNC_EVERY = 8


def train_epoch(step: Callable[[Any, dict], Tuple[Any, Any]],
                state: Any, host_batches: Iterator[dict],
                sharding: Optional[Any] = None,
                sync_every: int = SYNC_EVERY) -> Tuple[Any, float]:
    """Thread ``state`` through ``step(state, batch) -> (state, loss)``
    over one epoch of batches.

    With ``sharding`` the host batches are prefetched to device under it
    (each dict leaf placed with the same NamedSharding). ``step`` is the
    template's adapter around its jitted (usually donated) train_step.
    Returns (final state, mean loss as float).
    """
    import jax

    batches = (prefetch_to_device(host_batches, sharding=sharding)
               if sharding is not None else host_batches)
    losses = []
    for batch in batches:
        state, loss = step(state, batch)
        losses.append(loss)
        if sync_every and len(losses) % sync_every == 0:
            jax.block_until_ready(loss)
    if not losses:
        return state, float("nan")
    return state, float(np.mean([float(l) for l in losses]))
