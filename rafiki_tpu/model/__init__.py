"""Model-template plugin layer: contract, knobs, logging, dev harness.

This is the system's central interface (SURVEY.md §2 "Model contract").
"""

from .base import (BaseModel, Params, TrainContext, load_model_class,
                   serialize_model_class)
from .dev import test_model_class, tune_model, TuneResult
from .knob import (BaseKnob, CategoricalKnob, FixedKnob, FloatKnob,
                   IntegerKnob, KnobConfig, Knobs, PolicyKnob,
                   knob_config_from_json, knob_config_to_json, sample_knobs,
                   shape_signature, static_signature, traceable_knobs,
                   tunable_knobs, validate_knobs, validate_override_keys)
from .log import LogRecord, ModelLogger
from .loop import GangSpec, train_epoch
from .template_utils import bucketed_forward, conform_images, \
    same_tree_shapes

__all__ = [
    "bucketed_forward", "conform_images", "same_tree_shapes", "train_epoch",
    "BaseModel", "Params", "TrainContext", "load_model_class",
    "serialize_model_class", "test_model_class", "tune_model", "TuneResult",
    "BaseKnob", "CategoricalKnob", "FixedKnob", "FloatKnob", "IntegerKnob",
    "KnobConfig", "Knobs", "PolicyKnob", "knob_config_from_json",
    "knob_config_to_json", "sample_knobs", "shape_signature",
    "static_signature", "traceable_knobs", "tunable_knobs",
    "validate_knobs", "validate_override_keys", "LogRecord", "ModelLogger",
    "GangSpec",
]
