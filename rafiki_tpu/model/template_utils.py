"""Shared helpers for zoo templates (kept next to the contract so every
template uses one copy instead of re-implementing per file).

These are deliberately tiny and dependency-light: templates ship to workers
as standalone module source (see ``base.serialize_model_class``) and import
this via the absolute ``rafiki_tpu.model`` package.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np


def same_tree_shapes(a: Any, b: Any) -> bool:
    """True iff two pytrees share structure and leaf shapes. Warm-starting
    (SHARE_PARAMS) is only valid across trials with identical
    architectures, so this gates every shared-params load."""
    import jax

    ta = jax.tree_util.tree_structure(a)
    tb = jax.tree_util.tree_structure(b)
    if ta != tb:
        return False
    return all(getattr(x, "shape", None) == getattr(y, "shape", None)
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


def bucketed_forward(forward: Callable[..., Any], params: Any,
                     *xs: np.ndarray, bucket: int = 64) -> np.ndarray:
    """Run a jitted ``forward(params, *chunks)`` over per-example arrays
    ``xs`` in fixed-size zero-padded buckets: static shapes mean exactly
    one XLA compile per bucket size. ``forward`` must be cached by the
    caller (jit caches by function identity, so a fresh closure per call
    would recompile every time)."""
    n = len(xs[0])
    if n == 0:  # predict([]) / empty eval set: shape-probe, no compile
        import jax

        chunks = [np.zeros((bucket, *x.shape[1:]), x.dtype) for x in xs]
        probe = jax.eval_shape(forward, params, *chunks)
        return np.zeros((0, *probe.shape[1:]), np.dtype(probe.dtype))
    out = []
    for i in range(0, n, bucket):
        chunks = [x[i:i + bucket] for x in xs]
        pad = bucket - len(chunks[0])
        if pad:
            chunks = [np.concatenate(
                [c, np.zeros((pad, *c.shape[1:]), c.dtype)])
                for c in chunks]
        out.append(np.asarray(forward(params, *chunks))[:bucket - pad])
    return np.concatenate(out)


def conform_images(x: np.ndarray,
                   image_shape: Optional[Sequence[int]]) -> np.ndarray:
    """Pad/center-crop query images [N,H,W,C] to the train-time
    ``image_shape`` (H,W,C). Models with resolution-dependent parameters
    (ViT pos-embed, MLP flatten) crash on mismatched query sizes without
    this; channel counts must genuinely match and raise otherwise."""
    if image_shape is None:
        return x
    h, w, c = (int(v) for v in image_shape)
    if x.shape[-1] != c:
        if x.shape[-1] == 1:  # grayscale query against RGB-trained model
            x = np.repeat(x, c, axis=-1)
        else:
            raise ValueError(
                f"query has {x.shape[-1]} channels, model trained with {c}")
    # pad up
    ph, pw = max(0, h - x.shape[1]), max(0, w - x.shape[2])
    if ph or pw:
        x = np.pad(x, ((0, 0), (0, ph), (0, pw), (0, 0)))
    # center-crop down
    if x.shape[1] > h or x.shape[2] > w:
        oh = (x.shape[1] - h) // 2
        ow = (x.shape[2] - w) // 2
        x = x[:, oh:oh + h, ow:ow + w, :]
    return x
