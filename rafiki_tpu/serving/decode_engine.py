"""Continuous-batching decode engine for causal-LM serving.

Parity target: BASELINE.md config #5's "continuous-batch serving via
Predictor". The reference serves classifications by batching queued
queries per forward (SURVEY.md §3.3); generation needs more — requests
of different lengths must share the accelerator *mid-flight*. TPU-first
design:

- **One compiled step, fixed slots.** The engine owns a KV cache with
  ``max_slots`` rows and steps ALL slots in one jitted program per
  token. Static shapes: admission/completion never recompiles anything —
  a new request just changes the host-side slot table and the (tiny)
  per-slot token/position vectors fed each step.
- **Per-slot positions.** Each slot runs at its own depth (one mid-
  prompt, one mid-generation); the decoder writes each slot's KV at its
  own index (``models/llama_lora.py`` ``_DecoderAttention`` decode
  branch) and masks keys past it, so stale cache rows from a previous
  occupant are unreachable (a fresh slot starts at position 0).
- **Admission at step boundaries.** Between steps the host pulls queued
  requests into free slots: unified prefill/decode — a slot consumes
  its prompt token-by-token through the same step program, then flips
  to feeding back its own argmax. That is lockstep continuous batching:
  no separate prefill program, no pipeline bubble between phases.
- Completed slots detokenize/reply and free immediately; the step loop
  only runs while any slot is live, so an idle engine costs nothing.
- **Paged KV (block tables).** A module built with ``kv_page_size > 0``
  stores each layer's K/V in a ``(kv_pages, page_size, heads, dh)``
  POOL; every slot maps logical pages → pool pages through a small
  host-owned int32 table fed to each compiled call (static shape, so
  admission/allocation never recompiles). Pages are allocated lazily
  as a slot's position crosses page boundaries and freed at
  completion, so cache HBM and admission scale with LIVE tokens, not
  ``max_slots × max_len``. Admission reserves each request's
  worst-case pages (prompt + max_new, NOT max_len) up front — the
  accounting that makes mid-flight allocation infallible and
  backpressure deadlock-free: a request that does not fit the pool
  WAITS in the queue (``admission_stalls``) until completions free
  reservations, instead of being refused while memory sits idle.
  Token-bit-exact with the contiguous layout: attention gathers the
  row's pages back into logical order and the same position mask
  applies (stale bytes in unallocated/scratch pages sit past it).


The engine is token-level and model-agnostic: it needs a flax module
with the ``decode=True`` cache protocol. Text encode/detok is the
caller's job (``LlamaLoRA.make_decode_engine`` wires its tokenizer).
"""

from __future__ import annotations

import functools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.metrics import StatsMap
from ..ops.paged_attention import (resolve_paged_kernel,
                                   resolve_paged_window_kernel)
from .kv_tier import HostPageTier
from .kv_transfer import (LAYOUT_PAGED, LAYOUT_ROWS, check_kv_blob,
                          leaf_signature, make_kv_blob)
from .slo import (DEFAULT_SLO, ClassQueue, evictable_occupants,
                  normalize_slo, preemption_victim, slo_priority)

# Speculation break-even (tokens per verify call) and how many scan
# calls to wait before re-probing a gated-off speculator. ~1.5 means a
# draft window must beat single-token decoding by 50% to keep the
# verify path; re-probing is cheap (one call) and content can change.
SPEC_MIN_TOKENS_PER_CALL = 1.5
# draft-MODEL speculation pays two extra device dispatches per verify
# (draft scan + verify mirror) plus a mirror per plain scan, so its
# break-even floor sits higher than free host-side n-gram drafting
SPEC_MIN_TOKENS_PER_CALL_DRAFT = 2.2
SPEC_REPROBE_CALLS = 32
#: generated-token interval between decode_mark trace spans per slot —
#: coarse enough to stay off the hot path, fine enough that a stalled
#: generation shows WHERE it stalled in /debug/requests
SPAN_DECODE_MARK_EVERY = 32
# EMA decay for tokens-per-verify-call: 0.7 gates hopeless content off
# after ~2 zero-acceptance calls (start is just above the floor) while
# a healthy acceptance stream keeps the path on indefinitely
SPEC_EMA_DECAY = 0.7


@dataclass
class _Slot:
    request_id: Any
    prompt: np.ndarray          # (p,) int32, valid tokens only
    max_new: int
    temperature: float = 0.0    # <= 0 → greedy
    top_k: int = 0              # <= 0 → no top-k cut
    top_p: float = 1.0          # >= 1 → no nucleus cut
    seed: int = 0               # with (position) → the sample's PRNG key
    eos_id: Optional[int] = None  # emitting this token ends the request
    adapter_id: int = 0         # multi-adapter engines: which fine-tune
    slo: str = DEFAULT_SLO      # admission class (interactive first)
    seq: int = 0                # arrival order; preemption evicts the
    #                             YOUNGEST lowest-class victim
    n_consumed: int = 0         # tokens fed to the model so far
    generated: List[int] = field(default_factory=list)
    #: tokens generated BEFORE a preemption (re-ingested as prompt on
    #: resume, but still part of this request's OUTPUT): poll/
    #: poll_partial present prior + generated, so a preempted request
    #: resumes token-exact with nothing duplicated or lost
    prior: List[int] = field(default_factory=list)
    n_streamed: int = 0         # generated tokens already poll_partial'd
    first_tokened: bool = False  # first_token span already emitted
    #: admitted via the aging promotion (served ahead of waiting
    #: higher-priority work): immune to preemption — evicting it on
    #: the next interactive arrival would starve exactly the way
    #: aging exists to prevent
    shielded: bool = False
    #: disaggregated serving (prefill role): stop after chunked
    #: prefill and surface the slot's KV pages via ``poll_kv`` instead
    #: of generating — the shipment a decode-role worker installs
    prefill_only: bool = False
    #: disaggregated serving (decode role): a validated KV blob whose
    #: rows are installed at seat time, fast-forwarding the slot past
    #: the prefill the shipping worker already did
    kv_import: Optional[Dict[str, Any]] = None


class _Parked:
    """A slot suspended to the host KV tier: its lane is free, its
    pages live wherever the allocator put them (per logical page:
    still-resident HBM pool page, or a host-tier page), and every host
    mirror needed to reseat it rides along. Parking loses NO progress —
    unlike SLO preemption there is no re-prefill on resume; the
    restored pages ARE the KV the slot had."""

    __slots__ = ("slot", "pos", "tok", "stop_pos", "n_res", "pages",
                 "park_seq")

    def __init__(self, slot: _Slot, pos: int, tok: int, stop_pos: int,
                 n_res: int, pages: List[Tuple[str, int]],
                 park_seq: int) -> None:
        self.slot = slot
        self.pos = pos
        self.tok = tok
        self.stop_pos = stop_pos
        self.n_res = n_res
        self.pages = pages      # [("hbm", pool_page) | ("host", hp)]
        self.park_seq = park_seq

    def host_ids(self) -> List[int]:
        return [p for loc, p in self.pages if loc == "host"]

    def hbm_ids(self) -> List[int]:
        return [p for loc, p in self.pages if loc == "hbm"]


class DecodeEngine:
    """Slot-based continuous batching over one compiled decode step.

    ``steps_per_sync`` fuses K decode steps into ONE device program
    (``lax.scan``) with on-device input selection (next prompt token
    while prefilling, argmax feedback while generating). The host then
    pays one dispatch + one sync per K tokens instead of per token —
    the difference between per-token round-trips and streaming on a
    remote-execution TPU backend. Admission still happens at fused-step
    boundaries, so K trades a little admission latency for dispatch
    amortization. K=1 reproduces classic lockstep exactly; any K
    produces identical tokens (the selection logic is the same math).
    """

    def __init__(self, module: Any, params: Any, max_slots: int,
                 max_len: int, steps_per_sync: int = 4,
                 prefill_chunk: int = 32, speculate_k: int = 0,
                 draft: Optional[Tuple[Any, Any]] = None,
                 host_kv_pages: int = 0,
                 prefill_token_cost_s: float = 0.0) -> None:
        self.module = module
        self.params = params
        self.B = int(max_slots)
        self.L = int(max_len)
        self.K = max(1, int(steps_per_sync))
        #: >=2 enables greedy speculative decoding (prompt-lookup
        #: drafting, no draft model): each fused call verifies
        #: ``speculate_k - 1`` host-drafted tokens plus the model's own
        #: next token in ONE multi-token cache step, emitting 1..k
        #: tokens per call. Greedy-lossless: every emitted token is the
        #: model's argmax given its prefix, so outputs are identical to
        #: plain decoding — speculation only changes how many argmaxes
        #: one dispatch retires. Sampling slots fall back to the scan.
        self.spec_k = 0 if int(speculate_k) < 2 else min(int(speculate_k),
                                                         self.L)
        # acceptance gating: a verify call emits 1..k tokens for ONE
        # dispatch, while the fused scan emits K for one dispatch — at
        # low draft acceptance speculation would pay up to K× the
        # dispatch overhead it is meant to save. Track an EMA of tokens
        # emitted per speculative call; below the break-even floor the
        # engine falls back to the scan and re-probes periodically
        # (drafting quality is content-dependent and can recover).
        #: the EMA seeds just above the applicable floor AFTER the
        #: draft setup below (good content proves itself on call 1;
        #: bad content is gated after ~2 calls)
        self._spec_idle = 0  # scan calls since the last spec attempt
        #: prompt tokens ingested per fused prefill call (1 disables the
        #: separate prefill program — prompts then stream token-by-token
        #: through the decode scan like round-3 did). C-token prefill
        #: turns B (1, d)-matvec steps into (C, d) matmuls the MXU can
        #: tile, and pays 1/C as many dispatches for prompt ingestion.
        self.C = max(1, min(int(prefill_chunk), self.L))
        #: modeled prompt-compute floor (seconds per prompt token),
        #: slept on the loop thread after each prefill chunk. For
        #: benches/tests on hosts where the model under test is so
        #: small that prompt ingestion is ~free (tiny-model cpu
        #: fallback): production prompt forwards cost real wall time,
        #: and the prefill/decode interleave this engine schedules is
        #: invisible without it. 0 (the default) costs nothing.
        self.prefill_token_cost_s = max(0.0,
                                        float(prefill_token_cost_s))
        self._slots: List[Optional[_Slot]] = [None] * self.B
        #: class-aware admission queue (interactive > batch >
        #: background, FIFO within class, aging so background never
        #: starves). Caller-locked: every touch happens under _lock.
        self._cq = ClassQueue()
        self._seq = 0  # arrival stamp: preemption evicts youngest
        self._done: List[Tuple[Any, List[int]]] = []
        self._lock = threading.Lock()
        # host mirrors of the per-slot device inputs; prompts ride to the
        # device so mid-scan prefill continues without host involvement
        self._tok = np.zeros((self.B,), np.int32)
        self._pos = np.zeros((self.B,), np.int32)
        self._prompt_buf = np.zeros((self.B, self.L), np.int32)
        self._prompt_len = np.ones((self.B,), np.int32)
        self._stop_pos = np.zeros((self.B,), np.int32)
        # per-slot sampling config (device operands every fused step)
        self._temp = np.zeros((self.B,), np.float32)
        self._topk = np.zeros((self.B,), np.int32)
        self._topp = np.ones((self.B,), np.float32)
        self._seed = np.zeros((self.B,), np.int32)
        #: multi-adapter serving (module.n_adapters > 0): per-slot
        #: adapter selection, a device operand like the sampling knobs
        self.n_adapters = int(getattr(module, "n_adapters", 0) or 0)
        self._aid = np.zeros((self.B,), np.int32)
        #: device-resident prompt copy, refreshed only on admission — the
        #: (B, L) buffer must not ride host→device on every dispatch
        self._prompt_dev: Optional[jnp.ndarray] = None
        #: paged KV (module.kv_page_size > 0): host-owned page tables +
        #: free-list allocator over the module's (kv_pages, page_size,
        #: …) per-layer pools. Pool page 0 is the SCRATCH page — idle/
        #: free lanes write their idempotent re-feeds there and no slot
        #: ever owns it, so a zeroed table row is always safe to step.
        self.page_size = int(getattr(module, "kv_page_size", 0) or 0)
        self.paged = self.page_size > 0
        if self.paged:
            if self.L % self.page_size:
                raise ValueError(f"kv_page_size {self.page_size} must "
                                 f"divide max_len {self.L}")
            self.n_pages = int(getattr(module, "kv_pages", 0) or 0)
            if self.n_pages < 2:
                raise ValueError("paged KV needs kv_pages >= 2 (scratch"
                                 " page + at least one usable page)")
            self._n_table = self.L // self.page_size  # table width
            #: LIFO free list over pages 1..n_pages-1; reservation
            #: accounting (below) guarantees pops never fail mid-flight
            self._free_pages = list(range(self.n_pages - 1, 0, -1))
            self._n_alloc = np.zeros((self.B,), np.int32)
            #: worst-case pages reserved per slot at admission — the
            #: invariant sum(_n_res) <= budget (HBM usable pages, plus
            #: the host tier when one is attached) is what makes lazy
            #: allocation infallible and queue waits deadlock-free
            self._n_res = np.zeros((self.B,), np.int32)
            self._res_total = 0
        else:
            self._n_table = 1  # dummy operand keeps signatures uniform
        #: host-RAM KV page tier (``host_kv_pages > 0``, paged engines
        #: only): the admission budget becomes HBM + host pages. Cold
        #: pages — whole slots parked to make room for hotter work —
        #: evict to a pinned-host pool asynchronously and prefetch
        #: back ahead of the step that resumes them, so serviceable
        #: concurrency stops being hard-capped by HBM while the
        #: compiled step only ever touches HBM-resident pages.
        self.host_pages = int(host_kv_pages)
        if self.host_pages and not self.paged:
            raise ValueError("host_kv_pages requires a paged engine "
                             "(kv_page_size > 0): pages are the "
                             "tier's transfer unit")
        self.tier: Optional[HostPageTier] = None
        #: parked slots by a monotonic park key, insertion-ordered
        self._parked: Dict[int, _Parked] = {}
        self._park_seq = 0
        #: which paged-native Pallas kernels are live on this engine
        #: (module flag resolved against the backend — the ops-level
        #: dispatch rules)? ``paged_kernel_active``: the s==1 step
        #: kernel; ``paged_kernel_windowed``: the multi-token window
        #: kernel on top (chunked prefill + speculative verify).
        #: Surfaced as the ``paged_kernel_mode`` gauge (0 = gather /
        #: contiguous, 1 = step-only, 2 = windowed) so kernel-vs-gather
        #: fleets — and step-only escape-hatch fleets — are tellable
        #: apart on /metrics.
        _pk_flag = getattr(module, "paged_kernel", None)
        self.paged_kernel_active = bool(
            self.paged and resolve_paged_kernel(_pk_flag))
        self.paged_kernel_windowed = bool(
            self.paged_kernel_active
            and resolve_paged_window_kernel(_pk_flag))
        self.paged_kernel_mode = (2 if self.paged_kernel_windowed
                                  else 1 if self.paged_kernel_active
                                  else 0)
        self._ptab = np.zeros((self.B, self._n_table), np.int32)
        self._ptab_dev = jnp.asarray(self._ptab)
        self._ptab_dev_width = self._n_table
        self._ptab_dirty = False
        self._cache = module.init(
            jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
            decode=True)["cache"]
        # two compiled step programs: greedy-only traffic must not pay
        # the sampler's (B, vocab) sort per token (measured 18x slower
        # generation on CPU when it rode every step). The host picks per
        # fused call based on the live slots' temperatures.
        self._step_fns = {False: _make_step(module, self.B, self.K, False),
                          True: _make_step(module, self.B, self.K, True)}
        self._prefill_fn = (_make_prefill(module, self.B, self.C)
                            if self.C > 1 else None)
        #: narrow twin of the prefill program for short remainders: a
        #: 1-token admission walk must not pay a C-wide (B, C) matmul
        #: — at C=32 that call costs about one fused decode step, so
        #: every short-prompt admission used to stall all live streams
        #: by a step. Walks ≤ this width run the narrow program.
        self._small_c = 4
        self._prefill_fn_small = (
            _make_prefill(module, self.B, self._small_c)
            if self._prefill_fn is not None and self.C > self._small_c
            else None)
        self._verify_fn = (_make_verify(module, self.B, self.spec_k)
                           if self.spec_k else None)
        #: draft-MODEL speculation (``draft=(module, params)``, a
        #: smaller model sharing the vocab): replaces prompt-lookup
        #: drafting with real draft-model continuations. The draft
        #: keeps a slot-parallel KV cache synced by construction —
        #: every target cache advance (chunked prefill, fused scan,
        #: verify) is mirrored with one multi-token draft pass over
        #: the ACTUALLY-CONSUMED tokens, and accepted draft rows are
        #: definitionally the accepted tokens' KV (greedy acceptance
        #: means draft prediction == accepted token), so rejected rows
        #: are the standard unreachable-then-rewritten case. Greedy-
        #: lossless like prompt-lookup: the verify step is target-
        #: authoritative either way.
        self.draft_module, self.draft_params = draft or (None, None)
        self._draft_cache = None
        if self.draft_module is not None and self.spec_k:
            self._draft_cache = self.draft_module.init(
                jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
                decode=True)["cache"]
            # draft phase: k-1 greedy steps with argmax feedback
            self._draft_scan = _make_step(self.draft_module, self.B,
                                          self.spec_k - 1, False)
            # mirror passes: multi-token KV population (prefill-shaped)
            self._draft_sync_k = _make_prefill(self.draft_module,
                                               self.B, self.K)
            self._draft_sync_c = (_make_prefill(self.draft_module,
                                                self.B, self.C)
                                  if self.C > 1 else None)
            # verify mirror (chunk = spec_k): writes the verify call's
            # consumed inputs [tok, drafts] into the draft cache —
            # idempotent for rows the draft scan already wrote, and it
            # adds the final row the scan stops short of (needed when
            # a window is FULLY accepted: that row's KV must exist for
            # the draft's later attention)
            self._draft_sync_v = _make_prefill(self.draft_module,
                                               self.B, self.spec_k)
        #: draft-cost-aware break-even floor for the acceptance gate
        self._spec_floor = (SPEC_MIN_TOKENS_PER_CALL_DRAFT
                            if self._draft_cache is not None
                            else SPEC_MIN_TOKENS_PER_CALL)
        self._spec_ema = self._spec_floor + 0.5
        #: False while the gate is off and scan mirrors are skipped —
        #: a re-probe first rebuilds the draft cache from the slots'
        #: accepted contexts (cheaper than mirroring every gated scan)
        self._draft_synced = True
        #: registered shared prefix (system prompt): token ids, its
        #: precomputed 1-row KV cache, and its length. Requests whose
        #: prompt extends it skip its prefill — admission copies the
        #: snapshot rows into the slot's cache (bandwidth, not compute).
        #: one registered prefix PER ADAPTER (multi-tenant system
        #: prompts — a prefix's KV is a function of the adapter that
        #: computed it); single-adapter engines use key 0
        self._prefixes: Dict[int, Dict[str, Any]] = {}
        #: served-traffic counters + pool gauges, as a race-free
        #: ``obs.StatsMap`` (dict reads everywhere keep working; writes
        #: go through inc/set/max_set — see the obs-unregistered-metric
        #: lint rule). Gauge names are load-bearing: the worker, the
        #: /health aggregation, and the dashboard all key on them.
        self.stats = StatsMap({
            "steps": 0, "tokens_generated": 0, "requests_done": 0,
            "max_concurrent": 0, "prefill_calls": 0,
            "prefill_tokens": 0, "spec_calls": 0, "spec_drafted": 0,
            "spec_accepted": 0, "prefix_hits": 0, "prefix_tokens": 0,
            "spec_draft_model_calls": 0, "draft_resyncs": 0,
            # paged-KV pool observability (all 0 on contiguous
            # engines): current/peak pages physically allocated, the
            # usable pool size, and how many step() calls found the
            # head-of-queue request unable to reserve its worst case
            # (backpressure waits, not refusals)
            "kv_pages_used": 0, "kv_pages_high_water": 0,
            "kv_pages_total": (self.n_pages - 1 if self.paged else 0),
            "admission_stalls": 0,
            # SLO plane: mid-flight evictions of lower-class work so
            # an interactive request could admit (the victim resumes
            # token-exact from its re-queued prefix), aging promotions
            # (background served ahead of waiting interactive so it
            # never starves), and live per-class queue depths
            "preemptions": 0, "slo_aged_promotions": 0,
            "queued_interactive": 0, "queued_batch": 0,
            "queued_background": 0,
            # host-RAM KV tier (all 0 on untiered engines): host pool
            # occupancy, pages evicted to host over the engine's life,
            # prefetch effectiveness (a miss = the unpark had to pull
            # pages inline), raw bytes moved in both directions, and
            # live suspended-slot counts
            "kv_host_pages_used": 0,
            "kv_host_pages_total": self.host_pages,
            "kv_evictions_total": 0, "kv_prefetch_hits": 0,
            "kv_prefetch_misses": 0, "kv_transfer_bytes_total": 0,
            "kv_parked_slots": 0, "kv_unparks_total": 0,
            # disaggregated prefill/decode: KV page shipments produced
            # (prefill role) and installed (decode role) by this engine
            "kv_exports": 0, "kv_imports": 0,
            # which decode legs the Pallas block-table kernels serve:
            # 0 = page gather / contiguous, 1 = step-only (s==1 hot
            # loop; windows on the gather — the
            # RAFIKI_PAGED_KERNEL_WINDOWS=0 escape hatch), 2 = windowed
            # (chunked prefill + speculative verify too). The token
            # counters say how much traffic each kernel actually
            # carried: window tokens count prefill ingestion plus
            # verify-window rows, step tokens count fused-scan rows.
            "paged_kernel_mode": self.paged_kernel_mode,
            "paged_kernel_step_tokens": 0,
            "paged_kernel_window_tokens": 0})
        if self.host_pages:
            self.tier = HostPageTier(self.host_pages, self.stats)
        #: finished prefill-only shipments awaiting poll_kv
        self._done_kv: List[Tuple[Any, Dict[str, Any]]] = []
        #: optional request-lifecycle hook ``(event, request_id, attrs)``
        #: — the inference worker wires it into its trace buffer and
        #: latency histograms (TTFT, time-in-queue). Events: admitted,
        #: prefill, first_token, decode_mark (every
        #: ``SPAN_DECODE_MARK_EVERY`` generated tokens), done. None
        #: (the default) costs one attribute read per emission site.
        self.span_sink: Optional[Callable[[str, Any, Dict[str, Any]],
                                          None]] = None

    # ---- submission / results (thread-safe: worker loop vs callers) ----
    def submit(self, request_id: Any, prompt_ids: np.ndarray,
               max_new: int, temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed: int = 0,
               eos_id: Optional[int] = None,
               adapter_id: int = 0, slo: str = "",
               prefill_only: bool = False,
               kv_import: Optional[Dict[str, Any]] = None) -> None:
        """Queue a request. ``prompt_ids``: 1-D valid tokens (≥1); the
        prompt + generation must fit the cache (truncated to fit).

        Sampling is per-request and fully seeded: ``temperature <= 0``
        is greedy; otherwise top-k/top-p-filtered categorical sampling
        whose PRNG key is ``fold_in(PRNGKey(seed), position)`` — the
        draw at each position is a pure function of (seed, position),
        independent of batch composition, slot index, or
        ``steps_per_sync``, so generations are reproducible under any
        serving load.

        ``eos_id``: emitting this token finishes the request early (the
        EOS itself is dropped from the reply; tokens a fused call
        computed past it are discarded host-side and their cache rows
        are unreachable-then-rewritten, the standard slot-reuse
        invariant).

        ``adapter_id`` (multi-adapter engines only): which stacked
        fine-tune this request decodes under. Out-of-range ids raise
        ``ValueError`` — silently serving a DIFFERENT fine-tune would
        be a correct-looking wrong answer (each adapter is a different
        trial/tenant). Ignored on single-adapter engines.

        ``slo`` (``interactive`` / ``batch`` / ``background``, default
        interactive): admission class. Interactive admits first (FIFO
        within a class, aging so nothing starves) and may PREEMPT
        lower-class occupants when the pool/slots are full — the
        victim's pages free and it resumes token-exact later from its
        re-queued prefix. Unknown classes raise ``ValueError``."""
        prompt = np.asarray(prompt_ids, np.int32).ravel()
        max_new = max(1, min(int(max_new), self.L - 1))
        prompt = prompt[:max(1, self.L - max_new)]
        aid = self._check_adapter_id(adapter_id)
        cls = normalize_slo(slo)
        if kv_import is not None:
            # validated HERE (caller thread) so a bad shipment is a
            # structured refusal the worker can degrade on — never a
            # shape error escaping from the step thread mid-install
            flat = jax.tree_util.tree_leaves(self._cache)
            cov = int(kv_import.get("covered", 0) or 0) \
                if isinstance(kv_import, dict) else 0
            if self.paged:
                sig = [[list(c.shape[1:]), str(c.dtype)] for c in flat]
                lead = ((cov - 1) // self.page_size + 1) if cov else 0
            else:  # rows layout: leaves are (covered, heads, dh)
                sig = [[list(c.shape[2:]), str(c.dtype)] for c in flat]
                lead = cov
            kv_import = check_kv_blob(
                kv_import,
                layout=LAYOUT_PAGED if self.paged else LAYOUT_ROWS,
                page_size=self.page_size, expect_sig=sig,
                expect_leading=lead,
                prompt_len=len(prompt), adapter_id=aid)
        if self.paged:
            # a request whose worst case exceeds what can ever be
            # HBM-RESIDENT could never take a step — it would stall
            # the queue forever. Refuse loudly here; everything
            # smaller waits its turn (with a host tier the admission
            # BUDGET is larger, but residency is still HBM-bound).
            # Prefill-only work stops at the last prompt token, so its
            # worst case is the prompt walk alone.
            need = self._pages_for(
                max(1, len(prompt) - 1) if prefill_only
                else min(len(prompt) - 1 + max_new, self.L))
            if need > self.n_pages - 1:
                raise ValueError(
                    f"request needs {need} KV pages worst-case but the "
                    f"pool has {self.n_pages - 1} usable pages; raise "
                    "kv_pages or lower max_new/prompt length")
        with self._lock:
            self._seq += 1
            self._cq.push(cls, _Slot(
                request_id, prompt, max_new,
                temperature=float(temperature), top_k=int(top_k),
                top_p=float(top_p), seed=int(seed),
                eos_id=None if eos_id is None else int(eos_id),
                adapter_id=aid, slo=cls, seq=self._seq,
                prefill_only=bool(prefill_only),
                kv_import=kv_import))

    def _check_adapter_id(self, adapter_id: int) -> int:
        """Validate a request's adapter selection. Out-of-range ids
        raise — silently serving a DIFFERENT fine-tune would be a
        correct-looking wrong answer (each adapter is a different
        trial/tenant). Single-adapter engines ignore the field."""
        if self.n_adapters <= 0:
            return 0
        aid = int(adapter_id)
        if not 0 <= aid < self.n_adapters:
            raise ValueError(f"adapter_id {aid} out of range for "
                             f"{self.n_adapters}-adapter engine")
        return aid

    # ---- paged-KV allocator (host side, step-thread only: the lock
    # ---- protects queue/slots vs submitters; tables/free list are
    # ---- touched exclusively by the thread driving step()) ----
    def _pages_for(self, stop_pos: int) -> int:
        """Worst-case pages a request can touch: the scan path writes
        positions <= stop_pos - 1, and a speculative verify window can
        overwrite up to ``spec_k - 1`` past it (clamped to the cache).
        Reserved at admission so lazy allocation can never fail and a
        waiting queue can never deadlock."""
        h = min(stop_pos - 1 + (self.spec_k - 1 if self.spec_k else 0),
                self.L - 1)
        return h // self.page_size + 1

    @property
    def _budget_pages(self) -> int:
        """The two-tier admission budget: HBM usable pages plus the
        host tier. Reservations are granted against THIS total — the
        allocator invariant becomes sum(reservations) <= budget, and
        HBM shortfalls are resolved by evicting cold pages to host
        (:meth:`_reclaim_one_hbm_page`), which the invariant proves is
        always possible while any within-reservation growth is
        pending."""
        return self.n_pages - 1 + self.host_pages

    def _ensure_pages_to(self, i: int, last_pos: int,
                         have_lock: bool = False) -> None:
        """Allocate slot ``i``'s logical pages covering positions
        [0, last_pos] — called just before every compiled call with
        that call's write horizon (this is the LAZY part: a slot holds
        pages for where it is, not for max_len). With a host tier an
        empty free list is not failure: cold pages (parked slots
        first, then a freshly-parked victim's) evict to host until the
        growth fits — infallible by the combined-budget reservation
        invariant."""
        need = last_pos // self.page_size + 1
        grew = need > int(self._n_alloc[i])
        while int(self._n_alloc[i]) < need:
            if not self._free_pages:
                # only reachable on tiered engines (the untiered
                # invariant keeps the free list ahead of reservations)
                self._reclaim_one_hbm_page(protect=i,
                                           have_lock=have_lock)
            self._ptab[i, int(self._n_alloc[i])] = self._free_pages.pop()
            self._n_alloc[i] += 1
        if grew:
            self._ptab_dirty = True
            used = self.n_pages - 1 - len(self._free_pages)
            self.stats.set("kv_pages_used", used)
            self.stats.max_set("kv_pages_high_water", used)
            self.stats.set("kv_pages_total", self.n_pages - 1)

    # ---- host-tier mechanics (step thread; the tier's transfer
    # ---- thread only ever touches its own pool/staging state) ----
    def _reclaim_one_hbm_page(self, protect: int,
                              have_lock: bool = False) -> None:
        """Free at least one HBM pool page by evicting a cold page to
        the host tier: parked slots' still-resident pages first
        (coldest — nothing is stepping them), else park a live victim
        (never ``protect``) and evict from it. Raises only on an
        allocator-invariant breach (a bug, not an operating state)."""
        if self.tier is None:
            raise RuntimeError(
                "paged-KV allocator invariant breached: free list "
                "empty inside reservation and no host tier to spill "
                "to")
        if self._evict_parked_pages(limit=1, exclude_key=None):
            return
        j = self._park_victim(protect)
        if j is None:
            raise RuntimeError(
                "paged-KV allocator invariant breached: no free page, "
                "no parked cold page, and no parkable victim")
        self._park_slot(j, have_lock=have_lock)
        if not self._evict_parked_pages(limit=1, exclude_key=None):
            raise RuntimeError(
                "paged-KV allocator invariant breached: host tier "
                "full while within-reservation growth is pending")

    def _evict_parked_pages(self, limit: int,
                            exclude_key: Optional[int]) -> int:
        """Move up to ``limit`` HBM-resident pages of parked slots to
        the host tier (freeing their pool pages), taking from the
        LOWEST-priority / youngest parked slot first — the work least
        likely to resume next. Returns pages moved. The d2h copy runs
        on the tier thread; the freed pool pages are safe to reuse
        immediately (the gather dispatched here orders before any
        later donated step's writes)."""
        moved = 0
        order = sorted(
            (k for k in self._parked if k != exclude_key),
            key=lambda k: (slo_priority(self._parked[k].slot.slo),
                           self._parked[k].slot.seq),
            reverse=True)
        for k in order:
            if moved >= limit:
                break
            rec = self._parked[k]
            hbm = [(t, p) for t, (loc, p) in enumerate(rec.pages)
                   if loc == "hbm"]
            if not hbm:
                continue
            take = hbm[-(limit - moved):]  # tail pages: evict the
            #                                farthest-ahead KV first so
            #                                partial restores refill in
            #                                logical order
            host_ids = self.tier.alloc(len(take))
            if host_ids is None:
                if self.tier.free_pages() == 0:
                    break
                host_ids = self.tier.alloc(self.tier.free_pages())
                take = take[-len(host_ids):]
            pool_ids = [p for _t, p in take]
            idx = jnp.asarray(pool_ids, jnp.int32)
            leaves = [c[idx] for c in
                      jax.tree_util.tree_leaves(self._cache)]
            self.tier.evict_submit(host_ids, leaves)
            self.tier.drop_staged(k)  # staging for the old id set is
            #                           stale now; the prefetcher will
            #                           re-stage the grown set
            for (t, _p), h in zip(take, host_ids):
                rec.pages[t] = ("host", int(h))
            self._free_pages.extend(pool_ids)
            self._ptab_dirty = True
            moved += len(take)
        if moved:
            self.stats.set("kv_pages_used",
                           self.n_pages - 1 - len(self._free_pages))
        return moved

    def _park_victim(self, protect: int) -> Optional[int]:
        """The live slot to suspend when HBM must shrink: lowest
        class, youngest — mirroring the preemption order, but parking
        is allowed across classes and shields because NO progress is
        lost (the slot resumes from its exact KV, no re-prefill)."""
        cands = [j for j in range(self.B)
                 if j != protect and self._slots[j] is not None]
        if not cands:
            return None
        return max(cands, key=lambda j: (
            slo_priority(self._slots[j].slo), self._slots[j].seq))

    def _park_slot(self, j: int, have_lock: bool = False) -> None:
        """Suspend live slot ``j`` to the parked set: lane freed, host
        mirrors captured, pages kept (initially all HBM-resident —
        eviction moves them to host on demand). The reservation stays
        counted (the slot is still admitted work)."""
        slot = self._slots[j]
        n = int(self._n_alloc[j])
        self._park_seq += 1
        rec = _Parked(slot, pos=int(self._pos[j]),
                      tok=int(self._tok[j]),
                      stop_pos=int(self._stop_pos[j]),
                      n_res=int(self._n_res[j]),
                      pages=[("hbm", int(self._ptab[j, t]))
                             for t in range(n)],
                      park_seq=self._park_seq)
        self._slots[j] = None
        self._tok[j] = 0
        self._pos[j] = 0
        self._prompt_len[j] = 1
        self._stop_pos[j] = 0
        self._ptab[j, :] = 0
        self._n_alloc[j] = 0
        self._ptab_dirty = True
        if have_lock:
            self._n_res[j] = 0
        else:
            with self._lock:
                self._n_res[j] = 0
        self._parked[rec.park_seq] = rec
        if self._draft_cache is not None:
            # the draft cache's lane no longer mirrors this slot; the
            # next speculative re-probe rebuilds from accepted contexts
            self._draft_synced = False
        self.stats.set("kv_parked_slots", len(self._parked))
        self._span("parked", slot.request_id, slot=j,
                   pages=len(rec.pages))

    def _unpark_order(self) -> List[int]:
        """Resume order: highest class first, then oldest arrival —
        the inverse of the eviction order, so fill and evict work
        opposite ends of the parked set and the interleaved
        page-by-page exchange always converges."""
        return sorted(self._parked,
                      key=lambda k: (slo_priority(
                          self._parked[k].slot.slo),
                          self._parked[k].slot.seq))

    def _try_unpark(self) -> List[Tuple[int, _Parked, List[int],
                                        List[int], Any]]:
        """Admission-phase resume pass (lock held): restore parked
        slots' host pages into freshly-allocated HBM pages as capacity
        allows, and seat fully-resident parked slots into free lanes.
        Returns ``(install work, slots seated)`` — the installs are
        ``(lane, rec, pool_ids, host_ids, staged)`` tuples the caller
        scatters IMMEDIATELY, still under the lock: a later seat in
        the same admission pass may reclaim these very pages back to
        host, and a deferred install would let that eviction capture
        pre-install garbage (a silently-wrong resume)."""
        installs: List[Tuple[int, _Parked, List[int], List[int], Any]] \
            = []
        seated = 0
        for k in self._unpark_order():
            rec = self._parked[k]
            host = [(t, p) for t, (loc, p) in enumerate(rec.pages)
                    if loc == "host"]
            if host:
                fill = min(len(self._free_pages), len(host))
                if fill < len(host):
                    # not fully restorable yet: pull what fits (head
                    # pages first — logical order) and try again next
                    # step; evicting OTHER parked slots' pages to make
                    # room happens on demand in _reclaim_one_hbm_page
                    if fill == 0:
                        continue
                    host = host[:fill]
                pool_ids = [self._free_pages.pop() for _ in host]
                host_ids = [p for _t, p in host]
                staged = None
                if self.tier is not None and fill == len(
                        rec.host_ids()):
                    staged = self.tier.take_staged(k, host_ids)
                for (t, _p), pid in zip(host, pool_ids):
                    rec.pages[t] = ("hbm", int(pid))
                installs.append((-1, rec, pool_ids, host_ids, staged))
                self.stats.set(
                    "kv_pages_used",
                    self.n_pages - 1 - len(self._free_pages))
            if rec.host_ids():
                continue  # still partially host-resident
            i = next((j for j in range(self.B)
                      if self._slots[j] is None), None)
            if i is None:
                continue  # fully resident, waiting for a lane
            self._seat_parked(i, k, rec)
            seated += 1
        return installs, seated

    def _seat_parked(self, i: int, key: int, rec: _Parked) -> None:
        """Reseat a fully-HBM-resident parked slot into lane ``i``
        (lock held): mirrors restored, page table rebuilt, reservation
        moved back onto the lane. No re-prefill — the pages are the
        KV it had."""
        slot = rec.slot
        self._slots[i] = slot
        self._tok[i] = rec.tok
        self._pos[i] = rec.pos
        self._prompt_buf[i, :] = 0
        self._prompt_buf[i, :len(slot.prompt)] = slot.prompt
        self._prompt_len[i] = len(slot.prompt)
        self._stop_pos[i] = rec.stop_pos
        self._temp[i] = slot.temperature
        self._topk[i] = slot.top_k
        self._topp[i] = slot.top_p
        self._seed[i] = np.int32(slot.seed & 0x7FFFFFFF)
        self._aid[i] = slot.adapter_id
        for t, (loc, p) in enumerate(rec.pages):
            assert loc == "hbm"
            self._ptab[i, t] = p
        self._n_alloc[i] = len(rec.pages)
        self._n_res[i] = rec.n_res
        self._ptab_dirty = True
        del self._parked[key]
        if self.tier is not None:
            self.tier.drop_staged(key)
        self.stats.set("kv_parked_slots", len(self._parked))
        self.stats.inc("kv_unparks_total")

    def _apply_unpark_installs(self, installs) -> None:
        """Scatter restored pages' content into the cache (outside the
        engine lock, before any compiled call). Prefetch hits consume
        device arrays the tier thread staged; misses pull the host
        copies and upload inline (host→device — the direction that
        does not stall the device pipeline)."""
        for _lane, rec, pool_ids, host_ids, staged in installs:
            if staged is None:
                self.stats.inc("kv_prefetch_misses")
                leaves = self.tier.fetch(host_ids)
                staged = [jnp.asarray(a) for a in leaves]
                self.stats.inc("kv_transfer_bytes_total",
                               int(sum(a.nbytes for a in leaves)))
            else:
                self.stats.inc("kv_prefetch_hits")
            idx = jnp.asarray(pool_ids, jnp.int32)
            flat, treedef = jax.tree_util.tree_flatten(self._cache)
            flat = [c.at[idx].set(v.astype(c.dtype))
                    for c, v in zip(flat, staged)]
            self._cache = jax.tree_util.tree_unflatten(treedef, flat)
            self.tier.free(host_ids)
            self._span("unparked", rec.slot.request_id,
                       pages=len(pool_ids))

    def _prefetch_hint(self) -> None:
        """Tell the tier thread which parked slot resumes next so its
        host pages are staged as device arrays before the unpark needs
        them — the async path that keeps the compiled step from ever
        blocking on a transfer."""
        if self.tier is None or not self._parked:
            return
        for k in self._unpark_order():
            ids = self._parked[k].host_ids()
            if ids:
                self.tier.prefetch_submit(k, ids)
                return

    def _release_slot_pages(self, i: int, have_lock: bool = False
                            ) -> None:
        """Return slot ``i``'s pages + reservation to the pool (request
        completed or preempted): the table row points back at the
        scratch page, so the freed lane keeps stepping harmlessly.
        ``have_lock``: the SLO-preemption path calls this from inside
        the admission loop, which already holds ``_lock`` (the lock is
        not reentrant)."""
        n = int(self._n_alloc[i])
        if n:
            self._free_pages.extend(
                int(p) for p in self._ptab[i, :n])
            self._ptab[i, :n] = 0
            self._n_alloc[i] = 0
            self._ptab_dirty = True
        if have_lock:
            self._res_total -= int(self._n_res[i])
            self._n_res[i] = 0
        else:
            with self._lock:
                # reservation counters share the admission loop's lock
                # discipline (admission reads/writes them under _lock)
                self._res_total -= int(self._n_res[i])
                self._n_res[i] = 0
        self.stats.set("kv_pages_used",
                       self.n_pages - 1 - len(self._free_pages))
        self.stats.set("kv_pages_total", self.n_pages - 1)

    def _live_table_width(self) -> int:
        """Table columns the NEXT compiled call actually needs: enough
        to cover every slot's allocated pages (``_ensure_pages_to`` runs
        before every call, so ``_n_alloc`` already reflects that call's
        write horizon), rounded up to a power of two so the jit cache
        sees at most log2(max_len/page_size) distinct operand widths.
        Slicing the operand shrinks BOTH decode paths' per-step cost to
        live tokens: the gather fallback stops materializing (and
        soft-maxing over) dead pages, and the kernel's page grid stops
        iterating them."""
        hi = max(1, int(self._n_alloc.max()))
        w = 1
        while w < hi:
            w *= 2
        return min(w, self._n_table)

    def _ptab_arg(self) -> jnp.ndarray:
        """The page-table operand every compiled call consumes (a tiny
        constant zeros array on contiguous engines), re-uploaded only
        when allocation changed it — and sliced to the live width (see
        :meth:`_live_table_width`) on paged engines."""
        width = self._live_table_width() if self.paged else self._n_table
        if self._ptab_dirty or width != self._ptab_dev_width:
            self._ptab_dev = jnp.asarray(self._ptab[:, :width])
            self._ptab_dev_width = width
            self._ptab_dirty = False
        return self._ptab_dev

    def poll(self) -> List[Tuple[Any, List[int]]]:
        """Completed (request_id, generated ids) since the last poll."""
        with self._lock:
            done, self._done = self._done, []
        return done

    def poll_partial(self) -> List[Tuple[Any, List[int]]]:
        """(request_id, generated-so-far) for STILL-LIVE slots that
        produced new tokens since the last ``poll_partial``. Cumulative
        snapshots (copies), not deltas — the text layer re-detokenizes
        the whole sequence per event, which is what makes streaming
        byte-level BPE safe (a token boundary can split a multi-byte
        character; only the cumulative decode is well-formed). Call
        from the loop thread that drives ``step`` (same discipline as
        ``step`` itself); finished requests surface via ``poll``."""
        out: List[Tuple[Any, List[int]]] = []
        for slot in self._slots:
            if slot is None:
                continue
            total = len(slot.prior) + len(slot.generated)
            if total > slot.n_streamed:
                # prior + generated: a preempt-resumed request streams
                # its full output, never re-emitting the re-ingested
                # prefix (n_streamed carried across the preemption)
                out.append((slot.request_id,
                            slot.prior + list(slot.generated)))
                slot.n_streamed = total
        return out

    def poll_kv(self) -> List[Tuple[Any, Dict[str, Any]]]:
        """Completed prefill-only shipments since the last call:
        ``(request_id, KV blob)`` pairs ready to ride the hub to a
        decode-role engine's ``submit(..., kv_import=blob)``."""
        with self._lock:
            done, self._done_kv = self._done_kv, []
        return done

    def _harvest_prefill_only(self) -> None:
        """Complete prefill-only slots whose prompt walk reached its
        last token: extract the KV shipment, free the lane and pages.
        Runs after chunked prefill and costs one attribute scan when
        no prefill-role traffic exists."""
        shipped: List[Tuple[Any, Dict[str, Any]]] = []
        for i in range(self.B):
            s = self._slots[i]
            if s is None or not s.prefill_only:
                continue
            if int(self._pos[i]) >= len(s.prompt) - 1:
                shipped.append((s.request_id,
                                self._extract_slot_kv(i)))
                self._slots[i] = None
                self._tok[i] = 0
                self._pos[i] = 0
                self._prompt_len[i] = 1
                self._stop_pos[i] = 0
                if self.paged:
                    self._release_slot_pages(i)
        if shipped:
            with self._lock:
                self._done_kv.extend(shipped)
                self.stats.inc("requests_done", len(shipped))
            for rid, blob in shipped:
                self._span("prefilled", rid, covered=blob["covered"])

    def _extract_slot_kv(self, i: int) -> Dict[str, Any]:
        """Slot ``i``'s prefilled KV as a wire blob: the pages (paged)
        or rows (contiguous) covering positions ``0..pos-1``, every
        cache leaf uniformly (int8 pools and scale rows included)."""
        s = self._slots[i]
        covered = max(0, min(int(self._pos[i]), len(s.prompt) - 1))
        flat = jax.tree_util.tree_leaves(self._cache)
        leaves: List[np.ndarray] = []
        if covered:
            if self.paged:
                n = (covered - 1) // self.page_size + 1
                idx = jnp.asarray(self._ptab[i, :n], jnp.int32)
                dev = [c[idx] for c in flat]
            else:
                dev = [c[i, :covered] for c in flat]
            # the one sanctioned d2h sync outside the tier thread:
            # this is the prefill ROLE's shipment materialization —
            # by construction not the decode hot loop (prefill-only
            # slots never generate). One batched fetch for every
            # leaf, not a per-leaf round-trip.
            leaves = list(jax.device_get(dev))  # rafiki: noqa[blocking-transfer-in-decode-loop] — shipment materialization on the prefill leg, not the decode hot loop
        self.stats.inc("kv_exports")
        return make_kv_blob(
            covered, LAYOUT_PAGED if self.paged else LAYOUT_ROWS,
            self.page_size, leaves, adapter_id=s.adapter_id)

    def stage_kv_blob(self, blob: Dict[str, Any]) -> Dict[str, Any]:
        """Upload a shipment's leaves to device AHEAD of admission
        (call when the blob arrives off the wire, any thread). The
        h2d copies dispatch asynchronously and overlap whatever step
        is in flight, so the seat-time install pays one scatter
        dispatch instead of staging + scatter. Best-effort: on any
        failure the original host blob installs fine, just later."""
        try:
            staged = dict(blob)
            staged["leaves"] = [jnp.asarray(a)
                                for a in blob["leaves"]]
            return staged
        except Exception:  # noqa: BLE001 — staging is an overlap
            # optimization, never a correctness gate: the host blob
            # installs fine at seat time, just without the overlap
            import logging

            logging.getLogger(__name__).debug(
                "kv blob staging failed; installing from host",
                exc_info=True)
            return blob

    def _install_kv(self, i: int, blob: Dict[str, Any]) -> None:
        """Scatter a shipped blob's rows into slot ``i``'s pages/rows
        (validated at submit; pages allocated at seat). Upload
        direction only, through the donated installer — in-place on
        the cache buffers, O(shipped pages) device work; an eager
        ``at[].set`` here would copy the ENTIRE page pool per leaf on
        every install, a whole-HBM tax per arriving shipment."""
        cov = int(blob["covered"])
        staged = [jnp.asarray(a) for a in blob["leaves"]]
        flat, treedef = jax.tree_util.tree_flatten(self._cache)
        if self.paged:
            n = (cov - 1) // self.page_size + 1
            idx = jnp.asarray(self._ptab[i, :n], jnp.int32)
            flat = _install_pages(flat, idx, staged)
        else:
            flat = _install_rows(flat, jnp.int32(i), staged)
        self._cache = jax.tree_util.tree_unflatten(treedef, flat)
        self.stats.inc("kv_imports")
        self.stats.inc("kv_transfer_bytes_total",
                       int(blob.get("nbytes", 0) or 0))

    def register_prefix(self, prefix_ids: np.ndarray,
                        adapter_id: int = 0) -> int:
        """Precompute the KV cache of a shared prompt prefix (system
        prompt). Any later request whose prompt strictly extends these
        tokens skips their prefill: admission copies the snapshot's KV
        rows into the slot's cache — a device copy at HBM bandwidth
        instead of ``len(prefix)`` of model forward compute. Exact by
        construction (the copied KV is the same math prefill would
        produce); one prefix PER ADAPTER (re-register to replace, empty
        ids to clear).
        Returns the registered length (truncated to leave room for at
        least one prompt token + one generated token). Not safe to call
        concurrently with ``step`` (register before serving traffic, or
        between steps).

        ``adapter_id`` (multi-adapter engines): the prefix KV is a
        function of the adapter that computed it, so each adapter keeps
        its OWN registered prefix (multi-tenant system prompts) and
        hits are gated on the requesting slot's adapter."""
        aid = self._check_adapter_id(adapter_id)
        prefix = np.asarray(prefix_ids, np.int32).ravel()[:self.L - 2]
        if len(prefix) == 0:
            self._prefixes.pop(aid, None)
            return 0
        # snapshots compute through a CONTIGUOUS-cache twin of the
        # module even on paged engines: a 1-row (1, plen, …) snapshot
        # is the natural install source either way (the paged install
        # scatters it into the hit slots' pages)
        snap_module = (self.module.clone(kv_page_size=0, kv_pages=0)
                       if self.paged else self.module)
        cache1 = snap_module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
            decode=True)["cache"]
        # one multi-token cache pass over the prefix (same program shape
        # as chunked prefill, batch 1, chunk = len(prefix))
        fill = _make_prefill(snap_module, 1, len(prefix))
        snap = fill(self.params, cache1, jnp.asarray(prefix[None, :]),
                    jnp.arange(len(prefix), dtype=jnp.int32)[None, :],
                    jnp.asarray([aid], jnp.int32),
                    jnp.zeros((1, 1), jnp.int32))
        plen = len(prefix)
        install = _make_prefix_install(plen)
        # store only the populated rows: the snapshot allocates at
        # max_len but install() reads [:plen] — trimming cuts the
        # per-adapter resident HBM by max_len/plen
        snap = jax.tree_util.tree_map(lambda p: p[:, :plen], snap)
        snap = jax.block_until_ready(snap)
        if self.tier is not None:
            # host-tier engines keep the snapshot store in HOST memory
            # (numpy leaves): zero resident HBM while idle, uploaded
            # per install (jit device-puts host operands) — the same
            # capacity trade the page tier makes, and the form the
            # export/import shipment rides
            snap = jax.tree_util.tree_map(np.asarray, snap)
        entry = {"ids": prefix, "cache": snap,
                 "len": plen, "install": install, "aid": aid}
        if self._draft_cache is not None:
            # the draft attends the same positions: without its own
            # snapshot a prefix-hit slot would draft over zero KV for
            # 0..plen-1 (still lossless, but acceptance collapses and
            # the draft's cost is pure waste)
            d1 = self.draft_module.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
                decode=True)["cache"]
            d_fill = _make_prefill(self.draft_module, 1, plen)
            d_snap = d_fill(self.draft_params, d1,
                            jnp.asarray(prefix[None, :]),
                            jnp.arange(plen, dtype=jnp.int32)[None, :],
                            jnp.asarray([aid], jnp.int32),
                            jnp.zeros((1, 1), jnp.int32))
            d_snap = jax.tree_util.tree_map(lambda p: p[:, :plen],
                                            d_snap)
            entry["draft_cache"] = jax.block_until_ready(d_snap)
        self._prefixes[aid] = entry
        return plen

    def _install_prefix(self, rows: List[int],
                        pre: Dict[str, Any]) -> None:
        """Copy prefix ``pre``'s KV rows into the given slots (the
        same snapshot admission matched/fast-forwarded against). On a
        paged engine the snapshot scatters into the hit slots' pages
        (allocated at admission); the draft cache, always contiguous,
        keeps the row install."""
        rws = jnp.asarray(rows, jnp.int32)
        if self.paged:
            inst = _make_paged_prefix_install(pre["len"], self.page_size)
            self._cache = inst(
                self._cache, pre["cache"],
                jnp.asarray(self._ptab[np.asarray(rows, np.int64)],
                            jnp.int32))
        else:
            self._cache = pre["install"](self._cache, pre["cache"], rws)
        if self._draft_cache is not None and "draft_cache" in pre:
            self._draft_cache = pre["install"](
                self._draft_cache, pre["draft_cache"], rws)
        self.stats.inc("prefix_hits", len(rows))
        self.stats.inc("prefix_tokens", pre["len"] * len(rows))

    def export_prefix(self, adapter_id: int = 0
                      ) -> Optional[Dict[str, Any]]:
        """The registered prefix snapshot as a wire blob (msgpack-able
        numpy leaves): a shared prefix prefilled ONCE can serve every
        replica of a job — peers install it via
        :meth:`import_prefix` instead of re-running the prefill
        forward. None when no prefix is registered for the adapter."""
        pre = self._prefixes.get(self._check_adapter_id(adapter_id))
        if pre is None:
            return None
        leaves = [np.asarray(a) for a in
                  jax.tree_util.tree_leaves(pre["cache"])]
        return {"v": 1, "ids": np.asarray(pre["ids"], np.int32),
                "len": int(pre["len"]), "adapter_id": int(pre["aid"]),
                "sig": leaf_signature(leaves), "leaves": leaves,
                "nbytes": int(sum(a.nbytes for a in leaves))}

    def import_prefix(self, blob: Dict[str, Any],
                      adapter_id: int = 0) -> int:
        """Install a peer's exported prefix snapshot (see
        :meth:`export_prefix`) without recomputing its prefill.
        Validates geometry before touching state; raises
        ``ValueError`` on any mismatch. Draft-model engines fall back
        to undrafted prefix rows (still lossless — acceptance just
        starts cold until generation warms the draft cache). Returns
        the installed length. Same concurrency contract as
        :meth:`register_prefix` (not concurrent with ``step``)."""
        aid = self._check_adapter_id(adapter_id)
        if not isinstance(blob, dict) or int(blob.get("v", -1)) != 1:
            raise ValueError("not a prefix snapshot blob")
        ids = np.asarray(blob.get("ids"), np.int32).ravel()
        plen = int(blob.get("len", -1))
        if plen != len(ids) or not 0 < plen <= self.L - 2:
            raise ValueError(
                f"prefix blob length {plen} does not fit this engine "
                f"(1..{self.L - 2} tokens)")
        snap_module = (self.module.clone(kv_page_size=0, kv_pages=0)
                       if self.paged else self.module)
        cache1 = snap_module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
            decode=True)["cache"]
        flat, treedef = jax.tree_util.tree_flatten(cache1)
        leaves = [np.asarray(a) for a in blob.get("leaves") or []]
        if len(leaves) != len(flat) or any(
                v.shape[:2] != (1, plen) or v.shape[2:] != c.shape[2:]
                or v.dtype != c.dtype
                for v, c in zip(leaves, flat)):
            raise ValueError(
                "prefix blob does not match this engine's cache "
                "geometry (model shape / dtype / int8 mismatch)")
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
        if self.tier is None:
            tree = jax.tree_util.tree_map(jnp.asarray, tree)
        self._prefixes[aid] = {"ids": ids, "cache": tree, "len": plen,
                               "install": _make_prefix_install(plen),
                               "aid": aid}
        self.stats.inc("kv_imports")
        return plen

    @property
    def busy(self) -> bool:
        with self._lock:
            return bool(self._cq) or bool(self._parked) \
                or any(s is not None for s in self._slots)

    def reset_stats(self) -> None:
        """Zero the served-traffic counters without losing capacity
        gauges (``kv_pages_total`` describes the pool, not traffic) —
        what the worker's post-warmup scrub needs."""
        keep = {"paged_kernel_mode": self.paged_kernel_mode,
                "kv_host_pages_total": self.host_pages}
        if self.paged:
            keep.update(kv_pages_total=self.n_pages - 1,
                        kv_pages_used=(self.n_pages - 1
                                       - len(self._free_pages)))
        if self.tier is not None:
            keep.update(
                kv_host_pages_used=(self.host_pages
                                    - self.tier.free_pages()),
                kv_parked_slots=len(self._parked))
        self.stats.reset(keep=keep)

    def stats_snapshot(self) -> Dict[str, int]:
        """A point-in-time copy of the counters, taken under the stats
        lock — the ONLY race-free way to read them while the step
        thread runs (iterating ``stats`` key-by-key from another thread
        used to race concurrent mutation)."""
        return self.stats.snapshot()

    def _span(self, event: str, request_id: Any, **attrs: Any) -> None:
        """Emit a request-lifecycle event to the wired sink (no-op —
        one attribute read — when nothing is wired)."""
        sink = self.span_sink
        if sink is None:
            return
        try:
            sink(event, request_id, attrs)
        except Exception:  # noqa: BLE001 — observability must never
            import logging  # kill the step loop; log once per type

            logging.getLogger(__name__).warning(
                "span sink failed on %s", event, exc_info=True)
            self.span_sink = None  # a broken sink stays broken: detach

    def close(self) -> None:
        """Release the host tier's transfer thread and pinned pool.
        Idempotent; everything else dies with its references, but the
        tier's thread polls forever and its host pool is real RAM —
        a process that builds engines repeatedly (benches, tests,
        notebooks) must not accumulate one of each per engine."""
        if self.tier is not None:
            self.tier.close()

    def reset(self) -> None:
        """Drop all occupants and rebuild device state. For error
        recovery: a step that raised may have consumed the donated cache
        buffer, so the old cache must not be touched again."""
        with self._lock:
            self._slots = [None] * self.B
            self._cq.clear()
            self._done.clear()
            # host mirrors under the same lock: a submit() racing this
            # reset must observe either the old world or the cleared
            # one, never a half-cleared mix
            self._tok[:] = 0
            self._pos[:] = 0
            self._prompt_buf[:] = 0
            self._prompt_len[:] = 1
            self._stop_pos[:] = 0  # empty slots must be device-inactive
            self._temp[:] = 0.0
            self._topk[:] = 0
            self._topp[:] = 1.0
            self._seed[:] = 0
            self._aid[:] = 0
            self._prompt_dev = None
            self._spec_ema = self._spec_floor + 0.5
            self._spec_idle = 0
            self._draft_synced = True
            self._parked.clear()
            self._done_kv.clear()
            if self.tier is not None:
                self.tier.reset()
            self.stats.set("kv_parked_slots", 0)
            if self.paged:
                # every occupant is gone: the whole pool returns to the
                # free list and every table row points at scratch
                self._free_pages = list(range(self.n_pages - 1, 0, -1))
                self._ptab[:] = 0
                self._n_alloc[:] = 0
                self._n_res[:] = 0
                self._res_total = 0
                self._ptab_dirty = True
                self.stats.set("kv_pages_used", 0)
        self._cache = self.module.init(
            jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
            decode=True)["cache"]
        if self.draft_module is not None and self.spec_k:
            self._draft_cache = self.draft_module.init(
                jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
                decode=True)["cache"]

    def _chunked_prefill(self) -> None:
        """Ingest admitted prompts C tokens per compiled call before they
        join the decode scan (positions 0..plen−2; the scan then starts
        at the LAST prompt token, whose step emits the first generated
        token). Slots not prefilling re-feed their current input — an
        identical rewrite of a cache entry, harmless by construction —
        so one fixed-shape program serves any admission mix."""
        occupied = np.array([s is not None for s in self._slots],
                            bool)
        while True:
            rem = np.where(occupied,
                           np.maximum(0, (self._prompt_len - 1)
                                      - self._pos), 0)
            if rem.max() == 0:
                break
            fill_fn, c_use = self._prefill_fn, self.C
            if (self._prefill_fn_small is not None
                    and self._draft_cache is None
                    and rem.max() <= self._small_c):
                # short remainder: the narrow program ingests it
                # without the C-wide call's cost (the draft mirror is
                # compiled at C only, so draft engines stay wide)
                fill_fn, c_use = self._prefill_fn_small, self._small_c
            adv = np.minimum(rem, c_use)
            tok_chunk = np.empty((self.B, c_use), np.int32)
            pos_chunk = np.empty((self.B, c_use), np.int32)
            for i in range(self.B):
                a = int(adv[i])
                if a > 0:
                    p0 = int(self._pos[i])
                    tok_chunk[i, :a] = self._prompt_buf[i, p0:p0 + a]
                    pos_chunk[i, :a] = np.arange(p0, p0 + a)
                    # pad by repeating the chunk's last real entry —
                    # rewrites a just-written cache slot identically
                    tok_chunk[i, a:] = tok_chunk[i, a - 1]
                    pos_chunk[i, a:] = pos_chunk[i, a - 1]
                else:
                    tok_chunk[i, :] = self._tok[i]
                    pos_chunk[i, :] = self._pos[i]
            if self.paged:
                # lazy allocation tracks the prompt walk: each chunk
                # only maps the pages it is about to write. The slot
                # re-check matters on tiered engines: an earlier
                # lane's growth may have PARKED this one (page
                # reclaim) inside this very loop — its row is zeroed
                # and its pos reset, so ensuring pages here would
                # allocate for an empty lane and leak them
                for i in range(self.B):
                    if adv[i] > 0 and self._slots[i] is not None:
                        self._ensure_pages_to(
                            i, int(self._pos[i]) + int(adv[i]) - 1)
            tok_dev = jnp.asarray(tok_chunk)
            pos_dev = jnp.asarray(pos_chunk)
            aid_dev = jnp.asarray(self._aid)
            self._cache = fill_fn(
                self.params, self._cache, tok_dev, pos_dev, aid_dev,
                self._ptab_arg())
            if self._draft_cache is not None and self._draft_synced:
                # keep the draft's KV in lockstep with the prompt walk
                # (while desynced, resync rebuilds prompts anyway)
                self._draft_cache = self._draft_sync_c(
                    self.draft_params, self._draft_cache, tok_dev,
                    pos_dev, aid_dev, self._ptab_arg())
            self.stats.inc("prefill_calls")
            self.stats.inc("prefill_tokens", int(adv.sum()))
            if self.paged_kernel_windowed:
                # these prompt tokens attended through the window
                # kernel (the chunk call is an s=C window)
                self.stats.inc("paged_kernel_window_tokens",
                               int(adv.sum()))
            if self.prefill_token_cost_s:
                # outside the engine lock (step releases it before
                # prefill) so a dilated chunk stalls exactly what real
                # prompt compute would: this loop thread, nothing else
                time.sleep(self.prefill_token_cost_s * int(adv.sum()))
            for i in range(self.B):
                if adv[i] > 0 and self._slots[i] is not None:
                    # a lane parked mid-chunk (page reclaim) skips the
                    # advance: its record saved the PRE-chunk position,
                    # so the resume re-prefills this chunk — the
                    # chunk's writes went to the scratch page (its
                    # table row was zeroed at park), losing nothing
                    self._pos[i] += int(adv[i])
                    self._slots[i].n_consumed += int(adv[i])
                    self._tok[i] = self._prompt_buf[i, int(self._pos[i])]

    # ---- SLO preemption (lock held: admission-loop context) ----
    def _occupants(self, live_only: bool = False
                   ) -> List[Tuple[Any, str, int, bool]]:
        """Admitted work as the ``(handle, slo, seq, shielded)``
        tuples the shared eviction policy (`serving/slo.py`)
        consumes. Handles are ``("live", lane)`` for seated slots and
        ``("parked", key)`` for slots suspended to the host tier —
        parked work holds reservations (and host pages) too, so a
        higher-class head may reclaim them the same way."""
        occ: List[Tuple[Any, str, int, bool]] = [
            (("live", j), s.slo, s.seq, s.shielded)
            for j, s in enumerate(self._slots) if s is not None]
        if not live_only:
            occ.extend((("parked", k), r.slot.slo, r.slot.seq,
                        r.slot.shielded)
                       for k, r in self._parked.items())
        return occ

    def _victim_for(self, cls: str, live_only: bool = False
                    ) -> Optional[Any]:
        """The occupant to evict so a ``cls`` head can admit — the
        shared :func:`preemption_victim` policy (youngest
        lowest-class, shielded immune). ``live_only`` restricts to
        seated slots (a LANE can only come from a live victim; page
        reservations can come from parked ones too)."""
        return preemption_victim(cls, self._occupants(live_only))

    def _evictable_for(self, cls: str, live_only: bool = False
                       ) -> List[Any]:
        """Every occupant :meth:`_victim_for` could ever return for a
        ``cls`` head — the feasibility pre-check sums their
        reservations BEFORE committing any eviction (a preemption
        that cannot end in the head admitting would destroy the
        victims' progress for nothing; pre-SLO behavior just stalled
        in place with the lower-class work still running). Same
        predicate as victim selection BY CONSTRUCTION (both call
        :func:`evictable_occupants`), which is what guarantees the
        paged reclaim loop in :meth:`step` terminates in admission."""
        return [h for h, _s, _q in
                evictable_occupants(cls, self._occupants(live_only))]

    def _res_of(self, handle: Any) -> int:
        kind, ref = handle
        if kind == "live":
            return int(self._n_res[ref])
        return int(self._parked[ref].n_res)

    def _preempt_handle(self, handle: Any, by: str
                        ) -> Tuple[Any, int, int, str, str]:
        kind, ref = handle
        if kind == "live":
            return self._preempt_slot(ref, by)
        return self._preempt_parked(ref, by)

    def _resumed_from(self, slot: _Slot) -> _Slot:
        """The front-of-class re-queued request a preemption victim
        becomes: original prompt plus everything generated so far (the
        PR 7 forced-prefix shape) so re-admission re-ingests the
        prefix through chunked prefill at the SAME absolute positions
        — token-exact in every decode mode."""
        gen = list(slot.generated)
        prompt = (np.concatenate([slot.prompt,
                                  np.asarray(gen, np.int32)])
                  if gen else slot.prompt)
        resumed = _Slot(slot.request_id, prompt,
                        slot.max_new - len(gen),
                        temperature=slot.temperature, top_k=slot.top_k,
                        top_p=slot.top_p, seed=slot.seed,
                        eos_id=slot.eos_id,
                        adapter_id=slot.adapter_id, slo=slot.slo,
                        seq=slot.seq, prior=slot.prior + gen,
                        prefill_only=slot.prefill_only)
        resumed.n_streamed = slot.n_streamed
        resumed.first_tokened = slot.first_tokened
        resumed.shielded = slot.shielded
        return resumed

    def _preempt_parked(self, key: int, by: str
                        ) -> Tuple[Any, int, int, str, str]:
        """Evict a PARKED occupant: cheapest of all — nothing is
        seated, so its HBM pages, host pages, and reservation free
        immediately and it re-queues front-of-class exactly like a
        live victim (resumes token-exact later)."""
        rec = self._parked.pop(key)
        slot = rec.slot
        hbm = rec.hbm_ids()
        if hbm:
            self._free_pages.extend(hbm)
            self._ptab_dirty = True
        host = rec.host_ids()
        if host and self.tier is not None:
            self.tier.free(host)
        if self.tier is not None:
            self.tier.drop_staged(key)
        self._res_total -= rec.n_res
        self._cq.push(slot.slo, self._resumed_from(slot), front=True)
        self.stats.inc("preemptions")
        self.stats.set("kv_parked_slots", len(self._parked))
        self.stats.set("kv_pages_used",
                       self.n_pages - 1 - len(self._free_pages))
        return (slot.request_id, -1, len(slot.generated), slot.slo, by)

    def _preempt_slot(self, j: int, by: str
                      ) -> Tuple[Any, int, int, str, str]:
        """Evict slot ``j`` mid-generation so a higher-class admission
        fits. Cheap under paged KV: the victim's pages + reservation
        return to the pool NOW; the victim becomes a front-of-class
        re-queued request whose prompt is its original prompt PLUS
        everything generated so far (the PR 7 forced-prefix shape), so
        on re-admission it re-ingests that prefix through chunked
        prefill and continues at the SAME absolute positions —
        token-exact in every decode mode (greedy argmax depends only
        on history; sampled draws are pure functions of (seed,
        position); speculation is greedy-lossless; int8-KV and
        multi-adapter ride the same cache math). The vacated KV rows
        are the standard unreachable-then-rewritten slot-reuse case.
        Returns the ``preempted`` span record."""
        slot = self._slots[j]
        gen = list(slot.generated)
        resumed = self._resumed_from(slot)
        self._slots[j] = None
        self._tok[j] = 0
        self._pos[j] = 0  # fresh occupant restarts at position 0
        self._prompt_len[j] = 1
        self._stop_pos[j] = 0
        if self.paged:
            self._release_slot_pages(j, have_lock=True)
        self._cq.push(resumed.slo, resumed, front=True)
        self.stats.inc("preemptions")
        return (slot.request_id, j, len(gen), slot.slo, by)

    def _seat_slot(self, i: int, slot: _Slot) -> None:
        """Install a popped request into free slot ``i``: host mirrors,
        shared-prefix fast-forward (or a shipped-KV fast-forward for
        disaggregated decode), first lazy pages. Lock held.

        Content installs (prefix snapshot / shipped KV blob) happen
        HERE, immediately after the lane's pages are mapped — not
        batched after admission. On a tiered engine a LATER seat in
        the same admission pass can park this very lane and evict its
        pages to host; deferred installs would let that eviction
        capture pre-install garbage (a silently-wrong resume). The
        scatters are async dispatches; holding the lock across them
        costs submitters microseconds."""
        self._slots[i] = slot
        self._tok[i] = slot.prompt[0]
        self._pos[i] = 0
        self._prompt_buf[i, :] = 0
        self._prompt_buf[i, :len(slot.prompt)] = slot.prompt
        self._prompt_len[i] = len(slot.prompt)
        pre = self._prefixes.get(slot.adapter_id)
        install: Optional[Tuple[str, Any]] = None
        if slot.kv_import is not None \
                and int(slot.kv_import["covered"]) > 0:
            # disaggregated decode: a prefill-role worker already
            # computed positions 0..covered-1; the shipped rows
            # scatter into this slot's pages/rows (below, once the
            # pages are mapped) and the prompt walk resumes past them
            # — exactly the prefix-hit shape, sourced from the wire
            cov = int(slot.kv_import["covered"])
            self._pos[i] = cov
            slot.n_consumed = cov
            self._tok[i] = slot.prompt[cov]
            install = ("kv", slot.kv_import)
            slot.kv_import = None  # installed once; a preempt-resume
            #                        re-ingests through chunked prefill
        elif (pre is not None and len(slot.prompt) > pre["len"]
                and np.array_equal(slot.prompt[:pre["len"]],
                                   pre["ids"])):
            # shared-prefix hit: skip its prefill — the KV copy makes
            # positions 0..plen-1 as if prefilled, and the prompt walk
            # resumes at plen. `pre` is the snapshot the prompt
            # MATCHED, held through the install below — never a fresh
            # self._prefixes lookup a concurrent register could swap
            install = ("prefix", pre)
            self._pos[i] = pre["len"]
            slot.n_consumed = pre["len"]
            self._tok[i] = slot.prompt[pre["len"]]
        if slot.prefill_only:
            # prefill-role serving: stop at the last prompt token —
            # the position the decode leg starts from; the slot never
            # generates, its KV ships via poll_kv instead
            self._stop_pos[i] = max(0, len(slot.prompt) - 1)
        else:
            # finish once pos reaches plen - 1 + max_new (the step at
            # input position p emits a GENERATED token iff p >= plen-1)
            self._stop_pos[i] = min(
                len(slot.prompt) - 1 + slot.max_new, self.L)
        self._temp[i] = slot.temperature
        self._topk[i] = slot.top_k
        self._topp[i] = slot.top_p
        self._seed[i] = np.int32(slot.seed & 0x7FFFFFFF)
        self._aid[i] = slot.adapter_id
        if self.paged:
            # map the pages the slot starts on: position 0, or the
            # whole prefix/import span for a hit (the install below
            # scatters into them)
            self._ensure_pages_to(i, int(self._pos[i]),
                                  have_lock=True)
        if install is not None:
            kind, payload = install
            if kind == "kv":
                self._install_kv(i, payload)
            else:
                self._install_prefix([i], payload)

    # ---- the loop body ----
    def step(self) -> int:
        """Admit queued requests into free slots, run K fused compiled
        steps for every live slot, harvest completions. Returns live
        count (at admission time)."""
        admitted_info: List[Tuple[Any, int, int, str]] = []
        preempted_info: List[Tuple[Any, int, int, str, str]] = []
        with self._lock:
            # resume parked slots first: they hold reservations and
            # partial progress, and freeing their host pages is what
            # keeps the tier from silting up
            unpark_installs, n_unparked = self._try_unpark()
            if unpark_installs:
                # restored page CONTENT lands IMMEDIATELY (still under
                # the lock, before admission): a later seat's page
                # reclaim may evict these very pages back to host, and
                # it must evict their bytes, not pre-install garbage
                self._apply_unpark_installs(unpark_installs)
            admitted = n_unparked > 0
            while True:
                nxt = self._cq.peek()
                if nxt is None:
                    break
                cls, head = nxt
                i = next((j for j in range(self.B)
                          if self._slots[j] is None), None)
                # feasibility BEFORE any eviction: admission is
                # bounded by slots AND (paged) the page pool — the
                # head admits only if its worst case (prompt +
                # max_new + spec margin — its ACTUAL size, never
                # max_len) fits what is free plus what eviction could
                # reclaim from strictly-lower-class, non-shielded
                # occupants (parked ones included: their reservations
                # and pages free the same way). If even that is
                # insufficient, STALL WITHOUT evicting: destroying a
                # victim's progress while the head still cannot admit
                # would be pure loss (backpressure keeps FIFO
                # fairness — smaller latecomers never starve the
                # head; completions free reservations).
                victims = self._evictable_for(cls)
                live_victims = [h for h in victims if h[0] == "live"]
                if i is None and not live_victims:
                    break  # a lane can only come from a live victim
                n_res = 0
                if self.paged:
                    n_res = self._pages_for(
                        max(1, len(head.prompt) - 1)
                        if head.prefill_only
                        else min(len(head.prompt) - 1 + head.max_new,
                                 self.L))
                    avail = self._budget_pages - self._res_total
                    reclaim = sum(self._res_of(h) for h in victims)
                    if avail + reclaim < n_res:
                        self.stats.inc("admission_stalls")
                        break
                if i is None:
                    # every slot occupied: evict the youngest
                    # lowest-class LIVE occupant (pages return NOW —
                    # cheap under paged KV; the victim resumes
                    # token-exact later from its re-queued prefix)
                    h = self._victim_for(cls, live_only=True)
                    i = h[1]
                    preempted_info.append(self._preempt_slot(i, cls))
                if self.paged:
                    while self._res_total + n_res > self._budget_pages:
                        # guaranteed to terminate in admission by the
                        # feasibility check above; parked victims are
                        # the cheapest (nothing seated to destroy)
                        h = self._victim_for(cls)
                        preempted_info.append(
                            self._preempt_handle(h, cls))
                    self._n_res[i] = n_res
                    self._res_total += n_res
                # pop() == the peeked head: nothing ran between (a
                # preemption only pushes into strictly LOWER classes,
                # whose skip counters are unchanged)
                _, slot = self._cq.pop()
                if self._cq.last_pop_promoted:
                    slot.shielded = True  # aging fired: this slot may
                    #                       not be preempted in turn
                self._seat_slot(i, slot)
                admitted = True
                admitted_info.append((slot.request_id, i,
                                      len(slot.prompt), slot.slo,
                                      bool(slot.prior)))
            depths = self._cq.depths()
            self.stats.set("slo_aged_promotions", self._cq.promotions)
            live = [i for i in range(self.B) if self._slots[i] is not None]
            self.stats.max_set("max_concurrent",
                               len(live) + len(self._parked))
        for c, d in depths.items():
            self.stats.set(f"queued_{c}", d)
        # span emission OUTSIDE the engine lock: the sink may take its
        # own locks (trace buffer, histograms) and must not nest ours
        for rid, row, n_gen, vslo, by in preempted_info:
            self._span("preempted", rid, slot=row, tokens=n_gen,
                       slo=vslo, by=by)
        for rid, row, plen, cls, resumed in admitted_info:
            # `resumed` marks a preempt-resume RE-admission: observers
            # must not treat it as a fresh queue-wait sample (the gap
            # since submit includes the victim's pre-preemption
            # service time, not backlog)
            self._span("admitted", rid, slot=row, prompt_tokens=plen,
                       slo=cls, resumed=resumed)
        if not live:
            self._prefetch_hint()
            return 0
        if admitted and self._prefill_fn is not None:
            self._chunked_prefill()
            for rid, row, plen, cls, resumed in admitted_info:
                self._span("prefill", rid, prompt_tokens=plen)
        # prefill-only slots that reached their last prompt token are
        # done NOW: extract their KV shipment and free the lane before
        # the decode scan (they never generate)
        self._harvest_prefill_only()
        # chunked prefill / prefill-only harvest may have parked or
        # freed lanes: the scan must see the CURRENT occupancy
        live = [i for i in range(self.B) if self._slots[i] is not None]
        if admitted or self._prompt_dev is None:
            # refresh the device-resident prompts only when they changed
            self._prompt_dev = jnp.asarray(self._prompt_buf)
        self._prefetch_hint()
        if not live:
            return 0

        any_sampling = bool(any(
            self._slots[i] is not None and self._slots[i].temperature > 0
            for i in range(self.B)))
        # speculative path: all live slots greedy, past their prompts,
        # room for a full draft window in the cache, and recent
        # acceptance above break-even (or a periodic re-probe) —
        # otherwise this fused call runs the plain scan (the paths
        # interleave freely call-to-call; both emit exact argmax tokens)
        if (self._verify_fn is not None and not any_sampling
                and (self._spec_ema >= self._spec_floor
                     or self._spec_idle >= SPEC_REPROBE_CALLS)
                and all(self._pos[i] >= len(self._slots[i].prompt) - 1
                        and int(self._pos[i]) + self.spec_k <= self.L
                        for i in live)):
            return self._speculative_step(live)
        if self._verify_fn is not None:
            self._spec_idle += 1
        if self.paged:
            for i in live:
                # the fused scan writes positions pos..pos+K-1, frozen
                # at stop_pos-1: map exactly that window's pages. The
                # slot re-check guards tiered engines: an earlier
                # lane's growth can PARK this one inside this loop —
                # allocating for the emptied lane would leak its pages
                if self._slots[i] is None:
                    continue
                self._ensure_pages_to(i, min(
                    int(self._pos[i]) + self.K,
                    int(self._stop_pos[i])) - 1)
        self._cache, emitted = self._step_fns[any_sampling](
            self.params, self._cache, jnp.asarray(self._tok),
            jnp.asarray(self._pos), self._prompt_dev,
            jnp.asarray(self._prompt_len), jnp.asarray(self._stop_pos),
            jnp.asarray(self._temp), jnp.asarray(self._topk),
            jnp.asarray(self._topp), jnp.asarray(self._seed),
            jnp.asarray(self._aid), self._ptab_arg())
        emitted = np.asarray(emitted)  # rafiki: noqa[blocking-transfer-in-decode-loop] — the loop's OUTPUT sync: generated tokens must reach the host to stream; the fused K-step scan amortizes it
        self.stats.inc("steps", self.K)
        if self.paged_kernel_active:
            # every live lane ran K single-token steps through the
            # step kernel inside this fused call
            self.stats.inc(
                "paged_kernel_step_tokens",
                self.K * sum(1 for s in self._slots if s is not None))
        if self._draft_cache is not None:
            if not any_sampling and (
                    self._spec_ema >= self._spec_floor
                    or self._spec_idle >= SPEC_REPROBE_CALLS - 1):
                if not self._draft_synced:
                    self._resync_draft()
                self._mirror_scan_onto_draft(emitted)
            else:
                # speculation can't pay off right now (gate off, or
                # sampling slots block the all-greedy precondition):
                # skip the per-scan mirror — a draft engine must not be
                # slower than no draft — and let the next re-probe
                # rebuild the cache from accepted contexts
                self._draft_synced = False

        finished: List[Tuple[Any, List[int]]] = []
        for i in live:
            slot = self._slots[i]
            if slot is None:
                continue  # parked mid-call by a page reclaim: its
                #           lane idled through the scan (stop_pos 0)
            plen = len(slot.prompt)
            pos0 = int(self._pos[i])
            # steps this slot actually took inside the fused program
            # (slots that hit their stop mid-scan idle for the rest)
            n_real = max(0, min(self.K, int(self._stop_pos[i]) - pos0,
                                self.L - pos0))
            eos_hit = False
            n0 = len(slot.generated)
            for j in range(n_real):
                if pos0 + j >= plen - 1:  # emission at a generated pos
                    t = int(emitted[j, i])
                    if slot.eos_id is not None and t == slot.eos_id:
                        # EOS ends the request; drop it and whatever the
                        # fused call computed past it
                        eos_hit = True
                        break
                    slot.generated.append(t)
            n1 = len(slot.generated)
            if n1 > n0:
                self.stats.inc("tokens_generated", n1 - n0)
                self._mark_progress(slot, n0, n1)
            slot.n_consumed += n_real
            self._pos[i] = pos0 + n_real
            if (eos_hit or len(slot.generated) >= slot.max_new
                    or int(self._pos[i]) >= self.L):
                # prior + generated: a preempt-resumed request replies
                # with its FULL output (the re-ingested prefix counts)
                finished.append((slot.request_id,
                                 slot.prior + slot.generated))
                self._slots[i] = None
                self._tok[i] = 0
                self._pos[i] = 0  # fresh occupant restarts at position 0
                self._prompt_len[i] = 1
                self._stop_pos[i] = 0
                if self.paged:  # pages (and the reservation) free NOW,
                    self._release_slot_pages(i)  # not at slot reuse
            else:
                # reconstruct the next input host-side (mirrors the
                # on-device selection, so the next fused call continues
                # seamlessly)
                self._tok[i] = (slot.prompt[slot.n_consumed]
                                if slot.n_consumed < plen
                                else slot.generated[-1])
        if finished:
            with self._lock:
                self._done.extend(finished)
                self.stats.inc("requests_done", len(finished))
            for rid, toks in finished:
                self._span("done", rid, tokens=len(toks))
        return len(live)

    def _mark_progress(self, slot: "_Slot", n0: int, n1: int) -> None:
        """first_token / periodic decode_mark spans for a slot that
        grew from ``n0`` to ``n1`` generated tokens this call. Pure
        integer math when no sink is wired."""
        if self.span_sink is None:
            return
        if not slot.first_tokened:
            # flag, not n0 == 0: a preempt-resumed slot restarts its
            # generated list at 0 but its stream already first-tokened
            slot.first_tokened = True
            self._span("first_token", slot.request_id)
        if n0 // SPAN_DECODE_MARK_EVERY != n1 // SPAN_DECODE_MARK_EVERY:
            self._span("decode_mark", slot.request_id, tokens=n1)

    def _resync_draft(self) -> None:
        """Rebuild the draft cache from every live slot's ACCEPTED
        context (prompt + generated, positions 0..pos-1). Runs when a
        re-probe follows a gated-off stretch during which scan mirrors
        were skipped — a bounded number of K-chunk passes instead of a
        mirror on every gated scan."""
        self._draft_cache = self.draft_module.init(
            jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
            decode=True)["cache"]
        ctxs = {}
        maxp = 0
        for i in range(self.B):
            s = self._slots[i]
            if s is None:
                continue
            ctx = np.concatenate(
                [s.prompt, np.asarray(s.generated, np.int32)])
            ctxs[i] = ctx[:int(self._pos[i])]
            maxp = max(maxp, len(ctxs[i]))
        for c0 in range(0, maxp, self.K):
            tok_m = np.zeros((self.B, self.K), np.int32)
            pos_m = np.zeros((self.B, self.K), np.int32)
            for i in range(self.B):
                ctx = ctxs.get(i)
                if ctx is None or len(ctx) <= c0:
                    # nothing (left) for this lane: idempotent rewrite
                    # of its current token at its current position
                    tok_m[i, :] = self._tok[i]
                    pos_m[i, :] = self._pos[i]
                    continue
                n = min(self.K, len(ctx) - c0)
                tok_m[i, :n] = ctx[c0:c0 + n]
                pos_m[i, :n] = np.arange(c0, c0 + n)
                tok_m[i, n:] = tok_m[i, n - 1]
                pos_m[i, n:] = pos_m[i, n - 1]
            self._draft_cache = self._draft_sync_k(
                self.draft_params, self._draft_cache,
                jnp.asarray(tok_m), jnp.asarray(pos_m),
                jnp.asarray(self._aid), self._ptab_arg())
        self._draft_synced = True
        self.stats.inc("draft_resyncs")

    def _mirror_scan_onto_draft(self, emitted: np.ndarray) -> None:
        """Write the fused scan's ACTUALLY-CONSUMED inputs into the
        draft cache (one multi-token KV pass) so the draft stays
        token-for-token synced with the target through prompts,
        generation, and mixed admission — the invariant draft-model
        speculation relies on. Idle lanes re-write their current token
        at their current position (idempotent)."""
        tok_m = np.empty((self.B, self.K), np.int32)
        pos_m = np.empty((self.B, self.K), np.int32)
        for i in range(self.B):
            s = self._slots[i]
            p0 = int(self._pos[i])
            cur = int(self._tok[i])
            if s is None:
                tok_m[i, :] = cur
                pos_m[i, :] = p0
                continue
            plen = len(s.prompt)
            n_real = max(0, min(self.K, int(self._stop_pos[i]) - p0,
                                self.L - p0))
            for j in range(self.K):
                if j < n_real:
                    p = p0 + j
                    if j == 0:
                        t = cur
                    elif p < plen:
                        t = int(s.prompt[p])
                    else:  # generated region: the previous step's token
                        t = int(emitted[j - 1, i])
                    tok_m[i, j], pos_m[i, j] = t, p
                else:  # idle remainder: idempotent rewrite of the last
                    tok_m[i, j] = tok_m[i, j - 1] if j else cur
                    pos_m[i, j] = pos_m[i, j - 1] if j else p0
        self._draft_cache = self._draft_sync_k(
            self.draft_params, self._draft_cache, jnp.asarray(tok_m),
            jnp.asarray(pos_m), jnp.asarray(self._aid),
            self._ptab_arg())

    def _speculative_step(self, live: List[int]) -> int:
        """One verify call: host-drafted continuations for every live
        slot ride through a single multi-token cache step; each slot
        emits its accepted prefix plus the model's own token at the
        first mismatch (1..spec_k tokens). Rejected drafts leave stale
        KV rows ABOVE the slot's new position — unreachable by the
        position mask, and rewritten in place when generation reaches
        them (the admission-reuse invariant already relies on this)."""
        k = self.spec_k
        if self._draft_cache is not None:
            if not self._draft_synced:  # re-probe after a gated-off
                self._resync_draft()    # stretch with skipped mirrors
            # draft phase: k-1 fused greedy steps on the DRAFT model
            # (argmax feedback), advancing its synced cache; then the
            # verify mirror writes the window's inputs [tok, drafts]
            # so the final row exists for fully-accepted windows
            self._draft_cache, d_emit = self._draft_scan(
                self.draft_params, self._draft_cache,
                jnp.asarray(self._tok), jnp.asarray(self._pos),
                self._prompt_dev, jnp.asarray(self._prompt_len),
                jnp.asarray(self._stop_pos), jnp.asarray(self._temp),
                jnp.asarray(self._topk), jnp.asarray(self._topp),
                jnp.asarray(self._seed), jnp.asarray(self._aid),
                self._ptab_arg())
            drafts = np.asarray(d_emit).T.astype(np.int32)  # rafiki: noqa[blocking-transfer-in-decode-loop] — draft tokens feed the host-built verify operands; one pull per K-token window
            offs = np.arange(k, dtype=np.int32)[None, :]
            self._draft_cache = self._draft_sync_v(
                self.draft_params, self._draft_cache,
                jnp.asarray(np.concatenate(
                    [self._tok[:, None], drafts], axis=1)),
                jnp.asarray(self._pos[:, None] + offs),
                jnp.asarray(self._aid), self._ptab_arg())
            self.stats.inc("spec_draft_model_calls")
        else:
            drafts = np.zeros((self.B, k - 1), np.int32)
            for i in live:
                s = self._slots[i]
                ctx = np.concatenate(
                    [s.prompt, np.asarray(s.generated, np.int32)])
                drafts[i] = _ngram_draft(ctx, k - 1)
        if self.paged:
            for i in live:
                # the verify window writes positions pos..pos+k-1
                # (gated above to fit the cache); its pages must exist
                # even for drafts that end up rejected — the standard
                # unreachable-then-rewritten rows, inside reservation.
                # Slot re-check: a mid-loop park (tiered page reclaim)
                # empties a later lane — see _chunked_prefill
                if self._slots[i] is None:
                    continue
                self._ensure_pages_to(i, min(
                    int(self._pos[i]) + k - 1, self.L - 1))
        self._cache, g, n_emit = self._verify_fn(
            self.params, self._cache, jnp.asarray(self._tok),
            jnp.asarray(self._pos), jnp.asarray(drafts),
            jnp.asarray(self._stop_pos), jnp.asarray(self._aid),
            self._ptab_arg())
        g = np.asarray(g)            # rafiki: noqa[blocking-transfer-in-decode-loop] — verify OUTPUT sync: accepted tokens must reach the host to stream
        n_emit = np.asarray(n_emit)  # rafiki: noqa[blocking-transfer-in-decode-loop] — ditto (acceptance counts gate the host-side emit)
        self.stats.inc("steps")
        self.stats.inc("spec_calls")
        if self.paged_kernel_windowed:
            # each live lane attended a k-wide verify window through
            # the window kernel (the draft model's own mirror passes
            # stay contiguous and are not counted here)
            self.stats.inc("paged_kernel_window_tokens", k * len(live))
        self._spec_idle = 0
        self._spec_ema = (SPEC_EMA_DECAY * self._spec_ema
                          + (1 - SPEC_EMA_DECAY)
                          * float(np.mean(n_emit[live])))

        finished: List[Tuple[Any, List[int]]] = []
        for i in live:
            slot = self._slots[i]
            if slot is None:
                continue  # parked mid-call by a page reclaim
            pos0 = int(self._pos[i])
            take = max(1, min(int(n_emit[i]),
                              int(self._stop_pos[i]) - pos0,
                              self.L - pos0))
            toks = [int(t) for t in g[i, :take]]
            eos_hit = slot.eos_id is not None and slot.eos_id in toks
            if eos_hit:  # drop the EOS and anything verified past it
                toks = toks[:toks.index(slot.eos_id)]
            n0 = len(slot.generated)
            slot.generated.extend(toks)
            slot.n_consumed += take
            self._pos[i] = pos0 + take
            if toks:
                self.stats.inc("tokens_generated", len(toks))
                self._mark_progress(slot, n0, len(slot.generated))
            self.stats.inc("spec_drafted", k - 1)
            self.stats.inc("spec_accepted", take - 1)
            if (eos_hit or len(slot.generated) >= slot.max_new
                    or int(self._pos[i]) >= self.L):
                finished.append((slot.request_id,
                                 slot.prior + slot.generated))
                self._slots[i] = None
                self._tok[i] = 0
                self._pos[i] = 0
                self._prompt_len[i] = 1
                self._stop_pos[i] = 0
                if self.paged:
                    self._release_slot_pages(i)
            else:
                self._tok[i] = slot.generated[-1]
        if finished:
            with self._lock:
                self._done.extend(finished)
                self.stats.inc("requests_done", len(finished))
            for rid, toks in finished:
                self._span("done", rid, tokens=len(toks))
        return len(live)


def _ngram_draft(context: np.ndarray, k: int, max_n: int = 3) -> np.ndarray:
    """Prompt-lookup drafting: find the longest (≤ ``max_n``) suffix
    n-gram of ``context`` with an earlier occurrence and propose the
    ``k`` tokens that followed its most recent match; repeat-last when
    nothing matches. Pure host-side numpy — drafting costs no device
    time, and a bad draft costs nothing but its rejected verify lanes."""
    ctx = np.asarray(context, np.int32).ravel()
    n_ctx = len(ctx)
    for n in range(min(max_n, n_ctx - 1), 0, -1):
        suffix = ctx[n_ctx - n:]
        # windows over ctx[:-1]: every start whose n-gram ends before
        # the suffix's own final token
        windows = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
        hits = np.nonzero(np.all(windows == suffix, axis=1))[0]
        if len(hits):
            j = int(hits[-1]) + n  # continuation of the latest match
            cont = ctx[j:j + k]
            if len(cont) < k:
                cont = np.concatenate(
                    [cont, np.full(k - len(cont), ctx[-1], np.int32)])
            return cont.astype(np.int32)
    return np.full(k, ctx[-1], np.int32)


def _select_next(logits, temp, top_k, top_p, seed, pos):
    """Per-slot token selection on device: greedy when ``temp <= 0``,
    else temperature-scaled categorical over the top-k/top-p-filtered
    distribution. Both filters reduce to a per-row LOGIT THRESHOLD on
    the descending sort (k-th largest for top-k; the smallest logit of
    the minimal nucleus for top-p), so one sort serves both and the
    masked sample needs no gather back through sort order. The PRNG key
    is ``fold_in(fold_in(base, seed), position)`` — a pure function of
    (seed, position), so draws are reproducible under any batch
    composition, slot placement, or step fusion."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, -1).astype(jnp.int32)
    lg = logits / jnp.maximum(temp, 1e-6)[:, None]
    sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]  # descending
    kk = jnp.clip(jnp.where(top_k <= 0, v, top_k), 1, v)
    k_thresh = jnp.take_along_axis(
        sorted_lg, (kk - 1)[:, None].astype(jnp.int32), axis=-1)
    probs = jax.nn.softmax(sorted_lg, -1)
    cum = jnp.cumsum(probs, -1)
    # keep the minimal prefix whose mass reaches top_p (the first token
    # is always kept: its "mass before" is 0 < top_p)
    keep = (cum - probs) < jnp.maximum(top_p, 1e-6)[:, None]
    p_thresh = jnp.min(jnp.where(keep, sorted_lg, jnp.inf), -1,
                       keepdims=True)
    masked = jnp.where(lg >= jnp.maximum(k_thresh, p_thresh), lg, -1e30)
    base = jax.random.PRNGKey(0)
    keys = jax.vmap(lambda s, p: jax.random.fold_in(
        jax.random.fold_in(base, s), p))(seed, pos)
    sampled = jax.vmap(jax.random.categorical)(keys,
                                               masked).astype(jnp.int32)
    return jnp.where(temp <= 0.0, greedy, sampled)


@functools.lru_cache(maxsize=8)
def _make_step(module: Any, n_slots: int, k: int,
               sampling: bool) -> Callable:
    """K fused decode steps over all slots (cache donated in-place).

    On-device input selection between steps: while a slot's next
    position is still inside its prompt, the next input is the next
    prompt token (device-resident prompt buffer); afterwards it is the
    slot's own sampled/greedy token (``_select_next`` when ``sampling``,
    plain argmax otherwise — the greedy program never compiles the
    sampler's per-token vocab sort). Slots whose next position reaches
    ``stop_pos`` freeze (their tok/pos stop advancing) so a finished
    slot idles harmlessly for the remainder of the scan.

    Multi-adapter modules additionally consume the per-slot ``aid``
    operand (which stacked fine-tune each row decodes under); paged-KV
    modules the per-slot ``ptab`` page tables (a tiny ignored constant
    otherwise — one signature for both layouts)."""
    multi = int(getattr(module, "n_adapters", 0) or 0) > 0
    paged = int(getattr(module, "kv_page_size", 0) or 0) > 0

    @functools.partial(jax.jit, donate_argnums=(1,))
    def step_fn(params, cache, tok, pos, prompt_buf, prompt_len, stop_pos,
                temp, top_k, top_p, seed, aid, ptab):
        rows = jnp.arange(n_slots)

        def body(carry, _):
            cache, tok, pos = carry
            logits, muts = module.apply(
                {"params": params, "cache": cache}, tok[:, None],
                positions=pos[:, None], decode=True, mutable=["cache"],
                **({"adapter_ids": aid} if multi else {}),
                **({"page_tables": ptab} if paged else {}))
            lg = logits[:, -1].astype(jnp.float32)
            if sampling:
                nxt = _select_next(lg, temp, top_k, top_p, seed, pos)
            else:
                nxt = jnp.argmax(lg, -1).astype(jnp.int32)
            new_pos = pos + 1
            is_prefill = new_pos < prompt_len
            nxt_prompt = prompt_buf[
                rows, jnp.minimum(new_pos, prompt_buf.shape[1] - 1)]
            nxt_input = jnp.where(is_prefill, nxt_prompt, nxt)
            active = new_pos < stop_pos
            tok2 = jnp.where(active, nxt_input, tok)
            pos2 = jnp.where(active, new_pos, pos)
            return (muts["cache"], tok2, pos2), nxt

        (cache, tok, pos), emitted = jax.lax.scan(
            body, (cache, tok, pos), None, length=k)
        return cache, emitted  # (K, n_slots)

    return step_fn


@functools.lru_cache(maxsize=8)
def _make_verify(module: Any, n_slots: int, k: int) -> Callable:
    """One speculative verify step: feed each slot's current token plus
    its k-1 drafted continuations at positions pos..pos+k-1 through the
    decode-cache path (the chunked-prefill machinery — KV for the whole
    window is written before attention, and each query only sees keys
    at-or-before its own position). ``g[:, j]`` is the model's argmax
    AFTER input j, so draft j+1 is correct iff it equals ``g[:, j]``;
    ``n_emit`` = 1 + the length of the all-correct draft prefix — every
    emitted token is conditioned only on accepted history, which is what
    makes greedy speculation lossless. Free/finished slots re-feed their
    current token at their current position (an idempotent rewrite)."""

    multi = int(getattr(module, "n_adapters", 0) or 0) > 0
    paged = int(getattr(module, "kv_page_size", 0) or 0) > 0

    @functools.partial(jax.jit, donate_argnums=(1,))
    def verify_fn(params, cache, tok, pos, drafts, stop_pos, aid, ptab):
        active = (pos < stop_pos)[:, None]
        offs = jnp.arange(k)[None, :]
        seq = jnp.concatenate([tok[:, None], drafts], axis=1)
        seq = jnp.where(active, seq, tok[:, None])
        positions = jnp.where(active, pos[:, None] + offs, pos[:, None])
        logits, muts = module.apply(
            {"params": params, "cache": cache}, seq,
            positions=positions, decode=True, mutable=["cache"],
            **({"adapter_ids": aid} if multi else {}),
            **({"page_tables": ptab} if paged else {}))
        g = jnp.argmax(logits.astype(jnp.float32), -1).astype(jnp.int32)
        ok = jnp.cumprod((drafts == g[:, :-1]).astype(jnp.int32), axis=1)
        n_emit = 1 + jnp.sum(ok, axis=1).astype(jnp.int32)
        return muts["cache"], g, n_emit

    return verify_fn


@functools.lru_cache(maxsize=32)
def _make_prefix_install(plen: int) -> Callable:
    """Scatter a trimmed prefix snapshot into slot rows. Cached by
    prefix length so N same-text registrations (one per adapter in a
    multi-tenant boot) share ONE compiled program — only the forward
    prefill execution is genuinely per-adapter."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def install(cache, pre, rws):
        return jax.tree_util.tree_map(
            lambda c, p: c.at[rws, :plen].set(
                p[:, :plen].astype(c.dtype)), cache, pre)

    return install


@functools.lru_cache(maxsize=32)
def _make_paged_prefix_install(plen: int, page_size: int) -> Callable:
    """Paged-engine twin of :func:`_make_prefix_install`: scatter a
    (1, plen, …) contiguous snapshot into the hit slots' PAGES —
    ``tabs`` is the (n_rows, n_tables) page-table slice of exactly the
    rows being installed, whose prefix pages the engine allocated at
    admission. Cached by (length, page size) like its contiguous twin."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def install(cache, pre, tabs):
        pos = jnp.arange(plen)
        pg = tabs[:, pos // page_size]   # (n_rows, plen) pool pages
        off = pos % page_size            # (plen,) in-page offsets

        def put(c, p):
            vals = jnp.broadcast_to(
                p[:, :plen].astype(c.dtype),
                (tabs.shape[0], plen) + p.shape[2:])
            return c.at[pg, off].set(vals)

        return jax.tree_util.tree_map(put, cache, pre)

    return install


@functools.lru_cache(maxsize=8)
def _make_prefill(module: Any, n_slots: int, chunk: int) -> Callable:
    """One C-token prefill call: feed (B, C) tokens at their per-slot
    positions through the decode-cache path. The lm_head output is
    discarded (prefill emits nothing), so XLA dead-code-eliminates the
    (B, C, vocab) projection — the call is pure KV-cache population at
    matmul (not matvec) arithmetic intensity."""
    multi = int(getattr(module, "n_adapters", 0) or 0) > 0
    paged = int(getattr(module, "kv_page_size", 0) or 0) > 0

    @functools.partial(jax.jit, donate_argnums=(1,))
    def prefill_fn(params, cache, tok_chunk, pos_chunk, aid, ptab):
        _, muts = module.apply(
            {"params": params, "cache": cache}, tok_chunk,
            positions=pos_chunk, decode=True, mutable=["cache"],
            **({"adapter_ids": aid} if multi else {}),
            **({"page_tables": ptab} if paged else {}))
        return muts["cache"]

    return prefill_fn


@functools.partial(jax.jit, donate_argnums=(0,))
def _install_pages(flat: List[jnp.ndarray], idx: jnp.ndarray,
                   staged: List[jnp.ndarray]) -> List[jnp.ndarray]:
    """Shipment install, paged layout: scatter each staged leaf's
    pages into the donated cache leaves at ``idx``. Donation makes
    this an in-place write of the touched pages; the jit cache keys on
    (n_pages, leaf shapes), so one compile serves every same-length
    shipment engine-wide."""
    return [c.at[idx].set(v.astype(c.dtype))
            for c, v in zip(flat, staged)]


@functools.partial(jax.jit, donate_argnums=(0,))
def _install_rows(flat: List[jnp.ndarray], row: jnp.ndarray,
                  staged: List[jnp.ndarray]) -> List[jnp.ndarray]:
    """Shipment install, contiguous layout: write each staged leaf
    ``(covered, …)`` into the donated cache leaves at slot ``row``,
    positions ``0..covered-1``."""
    out = []
    for c, v in zip(flat, staged):
        upd = v.astype(c.dtype)[None]
        starts = (row,) + (jnp.int32(0),) * (c.ndim - 1)
        out.append(jax.lax.dynamic_update_slice(c, upd, starts))
    return out


class TextDecodeEngine:
    """Text-level wrapper: encode prompts, detokenize completions.

    ``encode(text) -> 1-D int32 ids`` and ``decode(ids) -> text`` come
    from the owning model template (see ``LlamaLoRA.make_decode_engine``).
    """

    #: the inference worker checks this before forwarding a failover
    #: request's ``forced_prefix`` (duck-typed user engines without the
    #: kwarg must get a structured rejection, not a TypeError that
    #: kills the serve thread)
    supports_resume = True
    #: ditto for the ``slo`` admission-class kwarg: the worker only
    #: forwards it to engines that declare the capability (a duck-typed
    #: user engine must degrade to classless FIFO, not TypeError)
    supports_slo = True
    #: ditto for disaggregated prefill/decode: ``submit_prefill`` /
    #: ``poll_kv`` on the prefill leg and ``submit(..., kv_blob=)`` on
    #: the decode leg — role-configured workers check this at boot so
    #: a duck-typed user engine fails the deploy, not the serve thread
    supports_kv_ship = True

    def __init__(self, engine: DecodeEngine,
                 encode: Callable[[str], np.ndarray],
                 decode: Callable[[List[int]], str],
                 max_new: int = 8, resume_sep: str = " ") -> None:
        self.engine = engine
        self._encode = encode
        self._decode = decode
        self.max_new = int(max_new)
        #: text joint between a prompt and a forced resume prefix (and
        #: between the prefix and the continuation decode): " " matches
        #: both tokenizer families — the hash tokenizer splits/joins on
        #: whitespace exactly, and the byte-BPE detok lstrips the
        #: leading space its first generated token usually carries
        self._sep = resume_sep
        self._stream_sent: Dict[Any, str] = {}  # rid -> text delivered
        #: rid -> forced resume prefix (failover re-submissions): the
        #: already-delivered text the engine re-ingests as prompt but
        #: which deltas/finals must present as generated output
        self._forced: Dict[Any, str] = {}
        #: resume requests whose prefix already covered the whole token
        #: budget: completed without touching the engine, surfaced on
        #: the next poll()
        self._forced_done: List[Tuple[Any, str]] = []

    def submit(self, request_id: Any, text: str,
               max_new: Optional[int] = None, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0, seed: int = 0,
               eos_id: Optional[int] = None, adapter_id: int = 0,
               forced_prefix: str = "", slo: str = "",
               kv_blob: Optional[Dict[str, Any]] = None) -> None:
        """``forced_prefix`` (streaming failover / client resume): text
        a previous worker already emitted for this request. It is
        re-ingested as part of the prompt (the engine's chunked-prefill
        path — prefix compute at matmul intensity, no decode steps),
        the token budget shrinks by the tokens it covers, and deltas /
        the final text present it as OUTPUT — the resumed stream
        continues exactly where the dead one stopped, without
        re-emitting or dropping text. Greedy continuations are
        token-exact whenever re-tokenizing prompt+prefix reproduces the
        original token boundaries (true for the whitespace tokenizer;
        byte-BPE may shift a boundary at the splice, in which case the
        predictor's replace/divergence machinery still keeps the client
        consistent)."""
        budget = self.max_new if max_new is None else int(max_new)
        if forced_prefix:
            full = text + self._sep + forced_prefix
            covered = max(0, len(self._encode(full))
                          - len(self._encode(text)))
            remaining = budget - covered
            if remaining <= 0:
                # the dead worker had already generated the whole
                # budget; only its final message was lost — complete
                # instantly with the prefix as the authoritative text
                self._forced_done.append((request_id,
                                          str(forced_prefix)))
                return
            self._forced[request_id] = str(forced_prefix)
            self._stream_sent[request_id] = str(forced_prefix)
            text, budget = full, remaining
            kv_blob = None  # a shipment covers the ORIGINAL prompt;
            # the resume prompt is longer, so re-ingest via chunked
            # prefill instead of installing mismatched rows
        kw = {}
        if kv_blob is not None:
            kw["kv_import"] = kv_blob
        self.engine.submit(request_id, self._encode(text), budget,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, seed=seed, eos_id=eos_id,
                           adapter_id=adapter_id, slo=slo, **kw)

    def submit_prefill(self, request_id: Any, text: str,
                       max_new: Optional[int] = None,
                       adapter_id: int = 0, slo: str = "") -> None:
        """Prefill-role submission (disaggregated serving): chew the
        prompt through chunked prefill and surface its KV shipment via
        :meth:`poll_kv` — no tokens are generated here; the decode leg
        installs the blob and runs the tight single-token loop."""
        self.engine.submit(request_id, self._encode(str(text)),
                           self.max_new if max_new is None
                           else int(max_new),
                           adapter_id=adapter_id, slo=slo,
                           prefill_only=True)

    def poll_kv(self) -> List[Tuple[Any, Dict[str, Any]]]:
        """Finished prefill-only shipments (see
        :meth:`DecodeEngine.poll_kv`)."""
        return self.engine.poll_kv()

    def stage_kv_blob(self, blob: Dict[str, Any]) -> Dict[str, Any]:
        """Pre-upload an arrived shipment's leaves (see
        :meth:`DecodeEngine.stage_kv_blob`)."""
        return self.engine.stage_kv_blob(blob)

    def export_prefix(self, adapter_id: int = 0):
        return self.engine.export_prefix(adapter_id=adapter_id)

    def import_prefix(self, blob, adapter_id: int = 0) -> int:
        return self.engine.import_prefix(blob, adapter_id=adapter_id)

    def _full_text(self, rid: Any, ids: List[int]) -> str:
        """The request's cumulative OUTPUT text: decoded generated ids,
        preceded by the forced resume prefix when one is active."""
        text = self._decode(ids)
        base = self._forced.get(rid)
        if base is not None:
            text = base + (self._sep + text if text else "")
        return text

    def poll(self) -> List[Tuple[Any, str]]:
        done = [(rid, self._full_text(rid, ids))
                for rid, ids in self.engine.poll()]
        done.extend(self._forced_done)
        self._forced_done = []
        for rid, _ in done:  # a finished request stops streaming state
            self._stream_sent.pop(rid, None)
            self._forced.pop(rid, None)
        return done

    def poll_partial(self) -> List[Tuple[Any, str]]:
        """(request_id, new text) for live requests since the last call.

        Each event re-detokenizes the cumulative ids and emits the text
        suffix past what was already delivered — cumulative decoding is
        the only well-formed view under byte-level BPE (a token boundary
        may split a multi-byte character, so per-token decodes are not
        concatenation-safe). Trailing replacement characters (U+FFFD —
        an incomplete UTF-8 sequence whose remaining bytes are still
        being generated) are WITHHELD until a later decode resolves
        them: emitted text comes only from byte-complete prefixes, so
        the delivered stream is append-only and deltas concatenate
        correctly. Genuinely invalid bytes (never completed) surface in
        the final text instead. Suffix-empty events are dropped."""
        out: List[Tuple[Any, str]] = []
        for rid, ids in self.engine.poll_partial():
            text = self._full_text(rid, ids).rstrip("�")
            sent = self._stream_sent.get(rid, "")
            if len(text) > len(sent) and text.startswith(sent):
                out.append((rid, text[len(sent):]))
                self._stream_sent[rid] = text
        return out

    def register_prefix(self, text: str, adapter_id: int = 0) -> int:
        """Precompute KV for a shared prompt prefix (system prompt);
        see :meth:`DecodeEngine.register_prefix`. Call before serving
        traffic (not concurrently with ``step``)."""
        return self.engine.register_prefix(self._encode(text),
                                           adapter_id=adapter_id)

    def step(self) -> int:
        return self.engine.step()

    def reset(self) -> None:
        self._stream_sent.clear()
        self._forced.clear()
        self._forced_done.clear()
        self.engine.reset()

    def close(self) -> None:
        self.engine.close()

    def reset_stats(self) -> None:
        self.engine.reset_stats()

    @property
    def busy(self) -> bool:
        return self.engine.busy

    @property
    def stats(self) -> Dict[str, int]:
        return self.engine.stats

    def stats_snapshot(self) -> Dict[str, int]:
        return self.engine.stats_snapshot()

    @property
    def span_sink(self):
        return self.engine.span_sink

    @span_sink.setter
    def span_sink(self, sink) -> None:
        # request ids pass through submit untouched, so the token
        # engine's lifecycle events carry the caller's ids directly
        self.engine.span_sink = sink
