"""Continuous-batching decode engine for causal-LM serving.

Parity target: BASELINE.md config #5's "continuous-batch serving via
Predictor". The reference serves classifications by batching queued
queries per forward (SURVEY.md §3.3); generation needs more — requests
of different lengths must share the accelerator *mid-flight*. TPU-first
design:

- **One compiled step, fixed slots.** The engine owns a KV cache with
  ``max_slots`` rows and steps ALL slots in one jitted program per
  token. Static shapes: admission/completion never recompiles anything —
  a new request just changes the host-side slot table and the (tiny)
  per-slot token/position vectors fed each step.
- **Per-slot positions.** Each slot runs at its own depth (one mid-
  prompt, one mid-generation); the decoder writes each slot's KV at its
  own index (``models/llama_lora.py`` ``_DecoderAttention`` decode
  branch) and masks keys past it, so stale cache rows from a previous
  occupant are unreachable (a fresh slot starts at position 0).
- **Admission at step boundaries.** Between steps the host pulls queued
  requests into free slots: unified prefill/decode — a slot consumes
  its prompt token-by-token through the same step program, then flips
  to feeding back its own argmax. That is lockstep continuous batching:
  no separate prefill program, no pipeline bubble between phases.
- Completed slots detokenize/reply and free immediately; the step loop
  only runs while any slot is live, so an idle engine costs nothing.

The engine is token-level and model-agnostic: it needs a flax module
with the ``decode=True`` cache protocol. Text encode/detok is the
caller's job (``LlamaLoRA.make_decode_engine`` wires its tokenizer).
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class _Slot:
    request_id: Any
    prompt: np.ndarray          # (p,) int32, valid tokens only
    max_new: int
    n_consumed: int = 0         # tokens fed to the model so far
    generated: List[int] = field(default_factory=list)


class DecodeEngine:
    """Slot-based continuous batching over one compiled decode step."""

    def __init__(self, module: Any, params: Any, max_slots: int,
                 max_len: int) -> None:
        self.module = module
        self.params = params
        self.B = int(max_slots)
        self.L = int(max_len)
        self._slots: List[Optional[_Slot]] = [None] * self.B
        self._queue: List[_Slot] = []
        self._done: List[Tuple[Any, List[int]]] = []
        self._lock = threading.Lock()
        # host mirrors of the per-slot device inputs
        self._tok = np.zeros((self.B,), np.int32)
        self._pos = np.zeros((self.B,), np.int32)
        self._cache = module.init(
            jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
            decode=True)["cache"]
        self._step_fn = _make_step(module, self.B)
        self.stats: Dict[str, int] = {
            "steps": 0, "tokens_generated": 0, "requests_done": 0,
            "max_concurrent": 0}

    # ---- submission / results (thread-safe: worker loop vs callers) ----
    def submit(self, request_id: Any, prompt_ids: np.ndarray,
               max_new: int) -> None:
        """Queue a request. ``prompt_ids``: 1-D valid tokens (≥1); the
        prompt + generation must fit the cache (truncated to fit)."""
        prompt = np.asarray(prompt_ids, np.int32).ravel()
        max_new = max(1, min(int(max_new), self.L - 1))
        prompt = prompt[:max(1, self.L - max_new)]
        with self._lock:
            self._queue.append(_Slot(request_id, prompt, max_new))

    def poll(self) -> List[Tuple[Any, List[int]]]:
        """Completed (request_id, generated ids) since the last poll."""
        with self._lock:
            done, self._done = self._done, []
        return done

    @property
    def busy(self) -> bool:
        with self._lock:
            return bool(self._queue) or any(s is not None
                                            for s in self._slots)

    def reset(self) -> None:
        """Drop all occupants and rebuild device state. For error
        recovery: a step that raised may have consumed the donated cache
        buffer, so the old cache must not be touched again."""
        with self._lock:
            self._slots = [None] * self.B
            self._queue.clear()
            self._done.clear()
        self._tok[:] = 0
        self._pos[:] = 0
        self._cache = self.module.init(
            jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
            decode=True)["cache"]

    # ---- the loop body ----
    def step(self) -> int:
        """Admit queued requests into free slots, run ONE compiled step
        for every live slot, harvest completions. Returns live count."""
        with self._lock:
            for i in range(self.B):
                if self._slots[i] is None and self._queue:
                    slot = self._queue.pop(0)
                    self._slots[i] = slot
                    self._tok[i] = slot.prompt[0]
                    self._pos[i] = 0
            live = [i for i in range(self.B) if self._slots[i] is not None]
            self.stats["max_concurrent"] = max(self.stats["max_concurrent"],
                                               len(live))
        if not live:
            return 0

        self._cache, nxt = self._step_fn(
            self.params, self._cache, jnp.asarray(self._tok),
            jnp.asarray(self._pos))
        nxt = np.asarray(nxt)
        self.stats["steps"] += 1

        finished: List[Tuple[Any, List[int]]] = []
        for i in live:
            slot = self._slots[i]
            slot.n_consumed += 1
            if slot.n_consumed < len(slot.prompt):
                # still prefilling: feed the next prompt token
                self._tok[i] = slot.prompt[slot.n_consumed]
            else:
                # generating: the model's output becomes the next input
                slot.generated.append(int(nxt[i]))
                self.stats["tokens_generated"] += 1
                self._tok[i] = nxt[i]
            self._pos[i] += 1
            if (len(slot.generated) >= slot.max_new
                    or int(self._pos[i]) >= self.L):
                finished.append((slot.request_id, slot.generated))
                self._slots[i] = None
                self._tok[i] = 0
                self._pos[i] = 0  # fresh occupant restarts at position 0
        if finished:
            with self._lock:
                self._done.extend(finished)
                self.stats["requests_done"] += len(finished)
        return len(live)


@functools.lru_cache(maxsize=8)
def _make_step(module: Any, n_slots: int) -> Callable:
    """One compiled decode step over all slots (cache donated in-place)."""

    @functools.partial(jax.jit, donate_argnums=(1,))
    def step_fn(params, cache, tok, pos):
        logits, muts = module.apply(
            {"params": params, "cache": cache}, tok[:, None],
            positions=pos[:, None], decode=True, mutable=["cache"])
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), -1)
        return muts["cache"], nxt.astype(jnp.int32)

    return step_fn


class TextDecodeEngine:
    """Text-level wrapper: encode prompts, detokenize completions.

    ``encode(text) -> 1-D int32 ids`` and ``decode(ids) -> text`` come
    from the owning model template (see ``LlamaLoRA.make_decode_engine``).
    """

    def __init__(self, engine: DecodeEngine,
                 encode: Callable[[str], np.ndarray],
                 decode: Callable[[List[int]], str],
                 max_new: int = 8) -> None:
        self.engine = engine
        self._encode = encode
        self._decode = decode
        self.max_new = int(max_new)

    def submit(self, request_id: Any, text: str,
               max_new: Optional[int] = None) -> None:
        self.engine.submit(request_id, self._encode(text),
                           self.max_new if max_new is None else max_new)

    def poll(self) -> List[Tuple[Any, str]]:
        return [(rid, self._decode(ids)) for rid, ids in self.engine.poll()]

    def step(self) -> int:
        return self.engine.step()

    def reset(self) -> None:
        self.engine.reset()

    @property
    def busy(self) -> bool:
        return self.engine.busy

    @property
    def stats(self) -> Dict[str, int]:
        return self.engine.stats
