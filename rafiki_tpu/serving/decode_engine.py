"""Continuous-batching decode engine for causal-LM serving.

Parity target: BASELINE.md config #5's "continuous-batch serving via
Predictor". The reference serves classifications by batching queued
queries per forward (SURVEY.md §3.3); generation needs more — requests
of different lengths must share the accelerator *mid-flight*. TPU-first
design:

- **One compiled step, fixed slots.** The engine owns a KV cache with
  ``max_slots`` rows and steps ALL slots in one jitted program per
  token. Static shapes: admission/completion never recompiles anything —
  a new request just changes the host-side slot table and the (tiny)
  per-slot token/position vectors fed each step.
- **Per-slot positions.** Each slot runs at its own depth (one mid-
  prompt, one mid-generation); the decoder writes each slot's KV at its
  own index (``models/llama_lora.py`` ``_DecoderAttention`` decode
  branch) and masks keys past it, so stale cache rows from a previous
  occupant are unreachable (a fresh slot starts at position 0).
- **Admission at step boundaries.** Between steps the host pulls queued
  requests into free slots: unified prefill/decode — a slot consumes
  its prompt token-by-token through the same step program, then flips
  to feeding back its own argmax. That is lockstep continuous batching:
  no separate prefill program, no pipeline bubble between phases.
- Completed slots detokenize/reply and free immediately; the step loop
  only runs while any slot is live, so an idle engine costs nothing.
- **Paged KV (block tables).** A module built with ``kv_page_size > 0``
  stores each layer's K/V in a ``(kv_pages, page_size, heads, dh)``
  POOL; every slot maps logical pages → pool pages through a small
  host-owned int32 table fed to each compiled call (static shape, so
  admission/allocation never recompiles). Pages are allocated lazily
  as a slot's position crosses page boundaries and freed at
  completion, so cache HBM and admission scale with LIVE tokens, not
  ``max_slots × max_len``. Admission reserves each request's
  worst-case pages (prompt + max_new, NOT max_len) up front — the
  accounting that makes mid-flight allocation infallible and
  backpressure deadlock-free: a request that does not fit the pool
  WAITS in the queue (``admission_stalls``) until completions free
  reservations, instead of being refused while memory sits idle.
  Token-bit-exact with the contiguous layout: attention gathers the
  row's pages back into logical order and the same position mask
  applies (stale bytes in unallocated/scratch pages sit past it).


The engine is token-level and model-agnostic: it needs a flax module
with the ``decode=True`` cache protocol. Text encode/detok is the
caller's job (``LlamaLoRA.make_decode_engine`` wires its tokenizer).
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.metrics import StatsMap
from ..ops.paged_attention import resolve_paged_kernel
from .slo import (DEFAULT_SLO, ClassQueue, evictable_occupants,
                  normalize_slo, preemption_victim)

# Speculation break-even (tokens per verify call) and how many scan
# calls to wait before re-probing a gated-off speculator. ~1.5 means a
# draft window must beat single-token decoding by 50% to keep the
# verify path; re-probing is cheap (one call) and content can change.
SPEC_MIN_TOKENS_PER_CALL = 1.5
# draft-MODEL speculation pays two extra device dispatches per verify
# (draft scan + verify mirror) plus a mirror per plain scan, so its
# break-even floor sits higher than free host-side n-gram drafting
SPEC_MIN_TOKENS_PER_CALL_DRAFT = 2.2
SPEC_REPROBE_CALLS = 32
#: generated-token interval between decode_mark trace spans per slot —
#: coarse enough to stay off the hot path, fine enough that a stalled
#: generation shows WHERE it stalled in /debug/requests
SPAN_DECODE_MARK_EVERY = 32
# EMA decay for tokens-per-verify-call: 0.7 gates hopeless content off
# after ~2 zero-acceptance calls (start is just above the floor) while
# a healthy acceptance stream keeps the path on indefinitely
SPEC_EMA_DECAY = 0.7


@dataclass
class _Slot:
    request_id: Any
    prompt: np.ndarray          # (p,) int32, valid tokens only
    max_new: int
    temperature: float = 0.0    # <= 0 → greedy
    top_k: int = 0              # <= 0 → no top-k cut
    top_p: float = 1.0          # >= 1 → no nucleus cut
    seed: int = 0               # with (position) → the sample's PRNG key
    eos_id: Optional[int] = None  # emitting this token ends the request
    adapter_id: int = 0         # multi-adapter engines: which fine-tune
    slo: str = DEFAULT_SLO      # admission class (interactive first)
    seq: int = 0                # arrival order; preemption evicts the
    #                             YOUNGEST lowest-class victim
    n_consumed: int = 0         # tokens fed to the model so far
    generated: List[int] = field(default_factory=list)
    #: tokens generated BEFORE a preemption (re-ingested as prompt on
    #: resume, but still part of this request's OUTPUT): poll/
    #: poll_partial present prior + generated, so a preempted request
    #: resumes token-exact with nothing duplicated or lost
    prior: List[int] = field(default_factory=list)
    n_streamed: int = 0         # generated tokens already poll_partial'd
    first_tokened: bool = False  # first_token span already emitted
    #: admitted via the aging promotion (served ahead of waiting
    #: higher-priority work): immune to preemption — evicting it on
    #: the next interactive arrival would starve exactly the way
    #: aging exists to prevent
    shielded: bool = False


class DecodeEngine:
    """Slot-based continuous batching over one compiled decode step.

    ``steps_per_sync`` fuses K decode steps into ONE device program
    (``lax.scan``) with on-device input selection (next prompt token
    while prefilling, argmax feedback while generating). The host then
    pays one dispatch + one sync per K tokens instead of per token —
    the difference between per-token round-trips and streaming on a
    remote-execution TPU backend. Admission still happens at fused-step
    boundaries, so K trades a little admission latency for dispatch
    amortization. K=1 reproduces classic lockstep exactly; any K
    produces identical tokens (the selection logic is the same math).
    """

    def __init__(self, module: Any, params: Any, max_slots: int,
                 max_len: int, steps_per_sync: int = 4,
                 prefill_chunk: int = 32, speculate_k: int = 0,
                 draft: Optional[Tuple[Any, Any]] = None) -> None:
        self.module = module
        self.params = params
        self.B = int(max_slots)
        self.L = int(max_len)
        self.K = max(1, int(steps_per_sync))
        #: >=2 enables greedy speculative decoding (prompt-lookup
        #: drafting, no draft model): each fused call verifies
        #: ``speculate_k - 1`` host-drafted tokens plus the model's own
        #: next token in ONE multi-token cache step, emitting 1..k
        #: tokens per call. Greedy-lossless: every emitted token is the
        #: model's argmax given its prefix, so outputs are identical to
        #: plain decoding — speculation only changes how many argmaxes
        #: one dispatch retires. Sampling slots fall back to the scan.
        self.spec_k = 0 if int(speculate_k) < 2 else min(int(speculate_k),
                                                         self.L)
        # acceptance gating: a verify call emits 1..k tokens for ONE
        # dispatch, while the fused scan emits K for one dispatch — at
        # low draft acceptance speculation would pay up to K× the
        # dispatch overhead it is meant to save. Track an EMA of tokens
        # emitted per speculative call; below the break-even floor the
        # engine falls back to the scan and re-probes periodically
        # (drafting quality is content-dependent and can recover).
        #: the EMA seeds just above the applicable floor AFTER the
        #: draft setup below (good content proves itself on call 1;
        #: bad content is gated after ~2 calls)
        self._spec_idle = 0  # scan calls since the last spec attempt
        #: prompt tokens ingested per fused prefill call (1 disables the
        #: separate prefill program — prompts then stream token-by-token
        #: through the decode scan like round-3 did). C-token prefill
        #: turns B (1, d)-matvec steps into (C, d) matmuls the MXU can
        #: tile, and pays 1/C as many dispatches for prompt ingestion.
        self.C = max(1, min(int(prefill_chunk), self.L))
        self._slots: List[Optional[_Slot]] = [None] * self.B
        #: class-aware admission queue (interactive > batch >
        #: background, FIFO within class, aging so background never
        #: starves). Caller-locked: every touch happens under _lock.
        self._cq = ClassQueue()
        self._seq = 0  # arrival stamp: preemption evicts youngest
        self._done: List[Tuple[Any, List[int]]] = []
        self._lock = threading.Lock()
        # host mirrors of the per-slot device inputs; prompts ride to the
        # device so mid-scan prefill continues without host involvement
        self._tok = np.zeros((self.B,), np.int32)
        self._pos = np.zeros((self.B,), np.int32)
        self._prompt_buf = np.zeros((self.B, self.L), np.int32)
        self._prompt_len = np.ones((self.B,), np.int32)
        self._stop_pos = np.zeros((self.B,), np.int32)
        # per-slot sampling config (device operands every fused step)
        self._temp = np.zeros((self.B,), np.float32)
        self._topk = np.zeros((self.B,), np.int32)
        self._topp = np.ones((self.B,), np.float32)
        self._seed = np.zeros((self.B,), np.int32)
        #: multi-adapter serving (module.n_adapters > 0): per-slot
        #: adapter selection, a device operand like the sampling knobs
        self.n_adapters = int(getattr(module, "n_adapters", 0) or 0)
        self._aid = np.zeros((self.B,), np.int32)
        #: device-resident prompt copy, refreshed only on admission — the
        #: (B, L) buffer must not ride host→device on every dispatch
        self._prompt_dev: Optional[jnp.ndarray] = None
        #: paged KV (module.kv_page_size > 0): host-owned page tables +
        #: free-list allocator over the module's (kv_pages, page_size,
        #: …) per-layer pools. Pool page 0 is the SCRATCH page — idle/
        #: free lanes write their idempotent re-feeds there and no slot
        #: ever owns it, so a zeroed table row is always safe to step.
        self.page_size = int(getattr(module, "kv_page_size", 0) or 0)
        self.paged = self.page_size > 0
        if self.paged:
            if self.L % self.page_size:
                raise ValueError(f"kv_page_size {self.page_size} must "
                                 f"divide max_len {self.L}")
            self.n_pages = int(getattr(module, "kv_pages", 0) or 0)
            if self.n_pages < 2:
                raise ValueError("paged KV needs kv_pages >= 2 (scratch"
                                 " page + at least one usable page)")
            self._n_table = self.L // self.page_size  # table width
            #: LIFO free list over pages 1..n_pages-1; reservation
            #: accounting (below) guarantees pops never fail mid-flight
            self._free_pages = list(range(self.n_pages - 1, 0, -1))
            self._n_alloc = np.zeros((self.B,), np.int32)
            #: worst-case pages reserved per slot at admission — the
            #: invariant sum(_n_res) <= n_pages - 1 is what makes lazy
            #: allocation infallible and queue waits deadlock-free
            self._n_res = np.zeros((self.B,), np.int32)
            self._res_total = 0
        else:
            self._n_table = 1  # dummy operand keeps signatures uniform
        #: is the paged-native Pallas decode kernel live on this engine
        #: (module flag resolved against the backend — the ops-level
        #: dispatch rule)? Surfaced as the ``paged_kernel_active``
        #: gauge so kernel-vs-gather fleets are tellable apart on
        #: /metrics.
        self.paged_kernel_active = bool(
            self.paged and resolve_paged_kernel(
                getattr(module, "paged_kernel", None)))
        self._ptab = np.zeros((self.B, self._n_table), np.int32)
        self._ptab_dev = jnp.asarray(self._ptab)
        self._ptab_dev_width = self._n_table
        self._ptab_dirty = False
        self._cache = module.init(
            jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
            decode=True)["cache"]
        # two compiled step programs: greedy-only traffic must not pay
        # the sampler's (B, vocab) sort per token (measured 18x slower
        # generation on CPU when it rode every step). The host picks per
        # fused call based on the live slots' temperatures.
        self._step_fns = {False: _make_step(module, self.B, self.K, False),
                          True: _make_step(module, self.B, self.K, True)}
        self._prefill_fn = (_make_prefill(module, self.B, self.C)
                            if self.C > 1 else None)
        self._verify_fn = (_make_verify(module, self.B, self.spec_k)
                           if self.spec_k else None)
        #: draft-MODEL speculation (``draft=(module, params)``, a
        #: smaller model sharing the vocab): replaces prompt-lookup
        #: drafting with real draft-model continuations. The draft
        #: keeps a slot-parallel KV cache synced by construction —
        #: every target cache advance (chunked prefill, fused scan,
        #: verify) is mirrored with one multi-token draft pass over
        #: the ACTUALLY-CONSUMED tokens, and accepted draft rows are
        #: definitionally the accepted tokens' KV (greedy acceptance
        #: means draft prediction == accepted token), so rejected rows
        #: are the standard unreachable-then-rewritten case. Greedy-
        #: lossless like prompt-lookup: the verify step is target-
        #: authoritative either way.
        self.draft_module, self.draft_params = draft or (None, None)
        self._draft_cache = None
        if self.draft_module is not None and self.spec_k:
            self._draft_cache = self.draft_module.init(
                jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
                decode=True)["cache"]
            # draft phase: k-1 greedy steps with argmax feedback
            self._draft_scan = _make_step(self.draft_module, self.B,
                                          self.spec_k - 1, False)
            # mirror passes: multi-token KV population (prefill-shaped)
            self._draft_sync_k = _make_prefill(self.draft_module,
                                               self.B, self.K)
            self._draft_sync_c = (_make_prefill(self.draft_module,
                                                self.B, self.C)
                                  if self.C > 1 else None)
            # verify mirror (chunk = spec_k): writes the verify call's
            # consumed inputs [tok, drafts] into the draft cache —
            # idempotent for rows the draft scan already wrote, and it
            # adds the final row the scan stops short of (needed when
            # a window is FULLY accepted: that row's KV must exist for
            # the draft's later attention)
            self._draft_sync_v = _make_prefill(self.draft_module,
                                               self.B, self.spec_k)
        #: draft-cost-aware break-even floor for the acceptance gate
        self._spec_floor = (SPEC_MIN_TOKENS_PER_CALL_DRAFT
                            if self._draft_cache is not None
                            else SPEC_MIN_TOKENS_PER_CALL)
        self._spec_ema = self._spec_floor + 0.5
        #: False while the gate is off and scan mirrors are skipped —
        #: a re-probe first rebuilds the draft cache from the slots'
        #: accepted contexts (cheaper than mirroring every gated scan)
        self._draft_synced = True
        #: registered shared prefix (system prompt): token ids, its
        #: precomputed 1-row KV cache, and its length. Requests whose
        #: prompt extends it skip its prefill — admission copies the
        #: snapshot rows into the slot's cache (bandwidth, not compute).
        #: one registered prefix PER ADAPTER (multi-tenant system
        #: prompts — a prefix's KV is a function of the adapter that
        #: computed it); single-adapter engines use key 0
        self._prefixes: Dict[int, Dict[str, Any]] = {}
        #: served-traffic counters + pool gauges, as a race-free
        #: ``obs.StatsMap`` (dict reads everywhere keep working; writes
        #: go through inc/set/max_set — see the obs-unregistered-metric
        #: lint rule). Gauge names are load-bearing: the worker, the
        #: /health aggregation, and the dashboard all key on them.
        self.stats = StatsMap({
            "steps": 0, "tokens_generated": 0, "requests_done": 0,
            "max_concurrent": 0, "prefill_calls": 0,
            "prefill_tokens": 0, "spec_calls": 0, "spec_drafted": 0,
            "spec_accepted": 0, "prefix_hits": 0, "prefix_tokens": 0,
            "spec_draft_model_calls": 0, "draft_resyncs": 0,
            # paged-KV pool observability (all 0 on contiguous
            # engines): current/peak pages physically allocated, the
            # usable pool size, and how many step() calls found the
            # head-of-queue request unable to reserve its worst case
            # (backpressure waits, not refusals)
            "kv_pages_used": 0, "kv_pages_high_water": 0,
            "kv_pages_total": (self.n_pages - 1 if self.paged else 0),
            "admission_stalls": 0,
            # SLO plane: mid-flight evictions of lower-class work so
            # an interactive request could admit (the victim resumes
            # token-exact from its re-queued prefix), aging promotions
            # (background served ahead of waiting interactive so it
            # never starves), and live per-class queue depths
            "preemptions": 0, "slo_aged_promotions": 0,
            "queued_interactive": 0, "queued_batch": 0,
            "queued_background": 0,
            # 1 while the Pallas block-table decode kernel serves this
            # engine's single-token steps (0 = page gather / contiguous)
            "paged_kernel_active": int(self.paged_kernel_active)})
        #: optional request-lifecycle hook ``(event, request_id, attrs)``
        #: — the inference worker wires it into its trace buffer and
        #: latency histograms (TTFT, time-in-queue). Events: admitted,
        #: prefill, first_token, decode_mark (every
        #: ``SPAN_DECODE_MARK_EVERY`` generated tokens), done. None
        #: (the default) costs one attribute read per emission site.
        self.span_sink: Optional[Callable[[str, Any, Dict[str, Any]],
                                          None]] = None

    # ---- submission / results (thread-safe: worker loop vs callers) ----
    def submit(self, request_id: Any, prompt_ids: np.ndarray,
               max_new: int, temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed: int = 0,
               eos_id: Optional[int] = None,
               adapter_id: int = 0, slo: str = "") -> None:
        """Queue a request. ``prompt_ids``: 1-D valid tokens (≥1); the
        prompt + generation must fit the cache (truncated to fit).

        Sampling is per-request and fully seeded: ``temperature <= 0``
        is greedy; otherwise top-k/top-p-filtered categorical sampling
        whose PRNG key is ``fold_in(PRNGKey(seed), position)`` — the
        draw at each position is a pure function of (seed, position),
        independent of batch composition, slot index, or
        ``steps_per_sync``, so generations are reproducible under any
        serving load.

        ``eos_id``: emitting this token finishes the request early (the
        EOS itself is dropped from the reply; tokens a fused call
        computed past it are discarded host-side and their cache rows
        are unreachable-then-rewritten, the standard slot-reuse
        invariant).

        ``adapter_id`` (multi-adapter engines only): which stacked
        fine-tune this request decodes under. Out-of-range ids raise
        ``ValueError`` — silently serving a DIFFERENT fine-tune would
        be a correct-looking wrong answer (each adapter is a different
        trial/tenant). Ignored on single-adapter engines.

        ``slo`` (``interactive`` / ``batch`` / ``background``, default
        interactive): admission class. Interactive admits first (FIFO
        within a class, aging so nothing starves) and may PREEMPT
        lower-class occupants when the pool/slots are full — the
        victim's pages free and it resumes token-exact later from its
        re-queued prefix. Unknown classes raise ``ValueError``."""
        prompt = np.asarray(prompt_ids, np.int32).ravel()
        max_new = max(1, min(int(max_new), self.L - 1))
        prompt = prompt[:max(1, self.L - max_new)]
        aid = self._check_adapter_id(adapter_id)
        cls = normalize_slo(slo)
        if self.paged:
            # a request whose worst case exceeds the whole pool could
            # NEVER admit — it would stall the FIFO queue forever.
            # Refuse loudly here; everything smaller waits its turn.
            need = self._pages_for(min(len(prompt) - 1 + max_new,
                                       self.L))
            if need > self.n_pages - 1:
                raise ValueError(
                    f"request needs {need} KV pages worst-case but the "
                    f"pool has {self.n_pages - 1} usable pages; raise "
                    "kv_pages or lower max_new/prompt length")
        with self._lock:
            self._seq += 1
            self._cq.push(cls, _Slot(
                request_id, prompt, max_new,
                temperature=float(temperature), top_k=int(top_k),
                top_p=float(top_p), seed=int(seed),
                eos_id=None if eos_id is None else int(eos_id),
                adapter_id=aid, slo=cls, seq=self._seq))

    def _check_adapter_id(self, adapter_id: int) -> int:
        """Validate a request's adapter selection. Out-of-range ids
        raise — silently serving a DIFFERENT fine-tune would be a
        correct-looking wrong answer (each adapter is a different
        trial/tenant). Single-adapter engines ignore the field."""
        if self.n_adapters <= 0:
            return 0
        aid = int(adapter_id)
        if not 0 <= aid < self.n_adapters:
            raise ValueError(f"adapter_id {aid} out of range for "
                             f"{self.n_adapters}-adapter engine")
        return aid

    # ---- paged-KV allocator (host side, step-thread only: the lock
    # ---- protects queue/slots vs submitters; tables/free list are
    # ---- touched exclusively by the thread driving step()) ----
    def _pages_for(self, stop_pos: int) -> int:
        """Worst-case pages a request can touch: the scan path writes
        positions <= stop_pos - 1, and a speculative verify window can
        overwrite up to ``spec_k - 1`` past it (clamped to the cache).
        Reserved at admission so lazy allocation can never fail and a
        waiting queue can never deadlock."""
        h = min(stop_pos - 1 + (self.spec_k - 1 if self.spec_k else 0),
                self.L - 1)
        return h // self.page_size + 1

    def _ensure_pages_to(self, i: int, last_pos: int) -> None:
        """Allocate slot ``i``'s logical pages covering positions
        [0, last_pos] — called just before every compiled call with
        that call's write horizon (this is the LAZY part: a slot holds
        pages for where it is, not for max_len)."""
        need = last_pos // self.page_size + 1
        grew = need > int(self._n_alloc[i])
        while int(self._n_alloc[i]) < need:
            # infallible by the reservation invariant (never more than
            # _n_res[i] <= free-at-admission pages per slot)
            self._ptab[i, int(self._n_alloc[i])] = self._free_pages.pop()
            self._n_alloc[i] += 1
        if grew:
            self._ptab_dirty = True
            used = self.n_pages - 1 - len(self._free_pages)
            self.stats.set("kv_pages_used", used)
            self.stats.max_set("kv_pages_high_water", used)
            self.stats.set("kv_pages_total", self.n_pages - 1)

    def _release_slot_pages(self, i: int, have_lock: bool = False
                            ) -> None:
        """Return slot ``i``'s pages + reservation to the pool (request
        completed or preempted): the table row points back at the
        scratch page, so the freed lane keeps stepping harmlessly.
        ``have_lock``: the SLO-preemption path calls this from inside
        the admission loop, which already holds ``_lock`` (the lock is
        not reentrant)."""
        n = int(self._n_alloc[i])
        if n:
            self._free_pages.extend(
                int(p) for p in self._ptab[i, :n])
            self._ptab[i, :n] = 0
            self._n_alloc[i] = 0
            self._ptab_dirty = True
        if have_lock:
            self._res_total -= int(self._n_res[i])
            self._n_res[i] = 0
        else:
            with self._lock:
                # reservation counters share the admission loop's lock
                # discipline (admission reads/writes them under _lock)
                self._res_total -= int(self._n_res[i])
                self._n_res[i] = 0
        self.stats.set("kv_pages_used",
                       self.n_pages - 1 - len(self._free_pages))
        self.stats.set("kv_pages_total", self.n_pages - 1)

    def _live_table_width(self) -> int:
        """Table columns the NEXT compiled call actually needs: enough
        to cover every slot's allocated pages (``_ensure_pages_to`` runs
        before every call, so ``_n_alloc`` already reflects that call's
        write horizon), rounded up to a power of two so the jit cache
        sees at most log2(max_len/page_size) distinct operand widths.
        Slicing the operand shrinks BOTH decode paths' per-step cost to
        live tokens: the gather fallback stops materializing (and
        soft-maxing over) dead pages, and the kernel's page grid stops
        iterating them."""
        hi = max(1, int(self._n_alloc.max()))
        w = 1
        while w < hi:
            w *= 2
        return min(w, self._n_table)

    def _ptab_arg(self) -> jnp.ndarray:
        """The page-table operand every compiled call consumes (a tiny
        constant zeros array on contiguous engines), re-uploaded only
        when allocation changed it — and sliced to the live width (see
        :meth:`_live_table_width`) on paged engines."""
        width = self._live_table_width() if self.paged else self._n_table
        if self._ptab_dirty or width != self._ptab_dev_width:
            self._ptab_dev = jnp.asarray(self._ptab[:, :width])
            self._ptab_dev_width = width
            self._ptab_dirty = False
        return self._ptab_dev

    def poll(self) -> List[Tuple[Any, List[int]]]:
        """Completed (request_id, generated ids) since the last poll."""
        with self._lock:
            done, self._done = self._done, []
        return done

    def poll_partial(self) -> List[Tuple[Any, List[int]]]:
        """(request_id, generated-so-far) for STILL-LIVE slots that
        produced new tokens since the last ``poll_partial``. Cumulative
        snapshots (copies), not deltas — the text layer re-detokenizes
        the whole sequence per event, which is what makes streaming
        byte-level BPE safe (a token boundary can split a multi-byte
        character; only the cumulative decode is well-formed). Call
        from the loop thread that drives ``step`` (same discipline as
        ``step`` itself); finished requests surface via ``poll``."""
        out: List[Tuple[Any, List[int]]] = []
        for slot in self._slots:
            if slot is None:
                continue
            total = len(slot.prior) + len(slot.generated)
            if total > slot.n_streamed:
                # prior + generated: a preempt-resumed request streams
                # its full output, never re-emitting the re-ingested
                # prefix (n_streamed carried across the preemption)
                out.append((slot.request_id,
                            slot.prior + list(slot.generated)))
                slot.n_streamed = total
        return out

    def register_prefix(self, prefix_ids: np.ndarray,
                        adapter_id: int = 0) -> int:
        """Precompute the KV cache of a shared prompt prefix (system
        prompt). Any later request whose prompt strictly extends these
        tokens skips their prefill: admission copies the snapshot's KV
        rows into the slot's cache — a device copy at HBM bandwidth
        instead of ``len(prefix)`` of model forward compute. Exact by
        construction (the copied KV is the same math prefill would
        produce); one prefix PER ADAPTER (re-register to replace, empty
        ids to clear).
        Returns the registered length (truncated to leave room for at
        least one prompt token + one generated token). Not safe to call
        concurrently with ``step`` (register before serving traffic, or
        between steps).

        ``adapter_id`` (multi-adapter engines): the prefix KV is a
        function of the adapter that computed it, so each adapter keeps
        its OWN registered prefix (multi-tenant system prompts) and
        hits are gated on the requesting slot's adapter."""
        aid = self._check_adapter_id(adapter_id)
        prefix = np.asarray(prefix_ids, np.int32).ravel()[:self.L - 2]
        if len(prefix) == 0:
            self._prefixes.pop(aid, None)
            return 0
        # snapshots compute through a CONTIGUOUS-cache twin of the
        # module even on paged engines: a 1-row (1, plen, …) snapshot
        # is the natural install source either way (the paged install
        # scatters it into the hit slots' pages)
        snap_module = (self.module.clone(kv_page_size=0, kv_pages=0)
                       if self.paged else self.module)
        cache1 = snap_module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
            decode=True)["cache"]
        # one multi-token cache pass over the prefix (same program shape
        # as chunked prefill, batch 1, chunk = len(prefix))
        fill = _make_prefill(snap_module, 1, len(prefix))
        snap = fill(self.params, cache1, jnp.asarray(prefix[None, :]),
                    jnp.arange(len(prefix), dtype=jnp.int32)[None, :],
                    jnp.asarray([aid], jnp.int32),
                    jnp.zeros((1, 1), jnp.int32))
        plen = len(prefix)
        install = _make_prefix_install(plen)
        # store only the populated rows: the snapshot allocates at
        # max_len but install() reads [:plen] — trimming cuts the
        # per-adapter resident HBM by max_len/plen
        snap = jax.tree_util.tree_map(lambda p: p[:, :plen], snap)
        entry = {"ids": prefix, "cache": jax.block_until_ready(snap),
                 "len": plen, "install": install, "aid": aid}
        if self._draft_cache is not None:
            # the draft attends the same positions: without its own
            # snapshot a prefix-hit slot would draft over zero KV for
            # 0..plen-1 (still lossless, but acceptance collapses and
            # the draft's cost is pure waste)
            d1 = self.draft_module.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
                decode=True)["cache"]
            d_fill = _make_prefill(self.draft_module, 1, plen)
            d_snap = d_fill(self.draft_params, d1,
                            jnp.asarray(prefix[None, :]),
                            jnp.arange(plen, dtype=jnp.int32)[None, :],
                            jnp.asarray([aid], jnp.int32),
                            jnp.zeros((1, 1), jnp.int32))
            d_snap = jax.tree_util.tree_map(lambda p: p[:, :plen],
                                            d_snap)
            entry["draft_cache"] = jax.block_until_ready(d_snap)
        self._prefixes[aid] = entry
        return plen

    def _install_prefix(self, rows: List[int],
                        pre: Dict[str, Any]) -> None:
        """Copy prefix ``pre``'s KV rows into the given slots (the
        same snapshot admission matched/fast-forwarded against). On a
        paged engine the snapshot scatters into the hit slots' pages
        (allocated at admission); the draft cache, always contiguous,
        keeps the row install."""
        rws = jnp.asarray(rows, jnp.int32)
        if self.paged:
            inst = _make_paged_prefix_install(pre["len"], self.page_size)
            self._cache = inst(
                self._cache, pre["cache"],
                jnp.asarray(self._ptab[np.asarray(rows)], jnp.int32))
        else:
            self._cache = pre["install"](self._cache, pre["cache"], rws)
        if self._draft_cache is not None and "draft_cache" in pre:
            self._draft_cache = pre["install"](
                self._draft_cache, pre["draft_cache"], rws)
        self.stats.inc("prefix_hits", len(rows))
        self.stats.inc("prefix_tokens", pre["len"] * len(rows))

    @property
    def busy(self) -> bool:
        with self._lock:
            return bool(self._cq) or any(s is not None
                                         for s in self._slots)

    def reset_stats(self) -> None:
        """Zero the served-traffic counters without losing capacity
        gauges (``kv_pages_total`` describes the pool, not traffic) —
        what the worker's post-warmup scrub needs."""
        keep = {"paged_kernel_active": int(self.paged_kernel_active)}
        if self.paged:
            keep.update(kv_pages_total=self.n_pages - 1,
                        kv_pages_used=(self.n_pages - 1
                                       - len(self._free_pages)))
        self.stats.reset(keep=keep)

    def stats_snapshot(self) -> Dict[str, int]:
        """A point-in-time copy of the counters, taken under the stats
        lock — the ONLY race-free way to read them while the step
        thread runs (iterating ``stats`` key-by-key from another thread
        used to race concurrent mutation)."""
        return self.stats.snapshot()

    def _span(self, event: str, request_id: Any, **attrs: Any) -> None:
        """Emit a request-lifecycle event to the wired sink (no-op —
        one attribute read — when nothing is wired)."""
        sink = self.span_sink
        if sink is None:
            return
        try:
            sink(event, request_id, attrs)
        except Exception:  # noqa: BLE001 — observability must never
            import logging  # kill the step loop; log once per type

            logging.getLogger(__name__).warning(
                "span sink failed on %s", event, exc_info=True)
            self.span_sink = None  # a broken sink stays broken: detach

    def reset(self) -> None:
        """Drop all occupants and rebuild device state. For error
        recovery: a step that raised may have consumed the donated cache
        buffer, so the old cache must not be touched again."""
        with self._lock:
            self._slots = [None] * self.B
            self._cq.clear()
            self._done.clear()
            # host mirrors under the same lock: a submit() racing this
            # reset must observe either the old world or the cleared
            # one, never a half-cleared mix
            self._tok[:] = 0
            self._pos[:] = 0
            self._prompt_buf[:] = 0
            self._prompt_len[:] = 1
            self._stop_pos[:] = 0  # empty slots must be device-inactive
            self._temp[:] = 0.0
            self._topk[:] = 0
            self._topp[:] = 1.0
            self._seed[:] = 0
            self._aid[:] = 0
            self._prompt_dev = None
            self._spec_ema = self._spec_floor + 0.5
            self._spec_idle = 0
            self._draft_synced = True
            if self.paged:
                # every occupant is gone: the whole pool returns to the
                # free list and every table row points at scratch
                self._free_pages = list(range(self.n_pages - 1, 0, -1))
                self._ptab[:] = 0
                self._n_alloc[:] = 0
                self._n_res[:] = 0
                self._res_total = 0
                self._ptab_dirty = True
                self.stats.set("kv_pages_used", 0)
        self._cache = self.module.init(
            jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
            decode=True)["cache"]
        if self.draft_module is not None and self.spec_k:
            self._draft_cache = self.draft_module.init(
                jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
                decode=True)["cache"]

    def _chunked_prefill(self) -> None:
        """Ingest admitted prompts C tokens per compiled call before they
        join the decode scan (positions 0..plen−2; the scan then starts
        at the LAST prompt token, whose step emits the first generated
        token). Slots not prefilling re-feed their current input — an
        identical rewrite of a cache entry, harmless by construction —
        so one fixed-shape program serves any admission mix."""
        occupied = np.array([s is not None for s in self._slots])
        while True:
            rem = np.where(occupied,
                           np.maximum(0, (self._prompt_len - 1)
                                      - self._pos), 0)
            if rem.max() == 0:
                break
            adv = np.minimum(rem, self.C)
            tok_chunk = np.empty((self.B, self.C), np.int32)
            pos_chunk = np.empty((self.B, self.C), np.int32)
            for i in range(self.B):
                a = int(adv[i])
                if a > 0:
                    p0 = int(self._pos[i])
                    tok_chunk[i, :a] = self._prompt_buf[i, p0:p0 + a]
                    pos_chunk[i, :a] = np.arange(p0, p0 + a)
                    # pad by repeating the chunk's last real entry —
                    # rewrites a just-written cache slot identically
                    tok_chunk[i, a:] = tok_chunk[i, a - 1]
                    pos_chunk[i, a:] = pos_chunk[i, a - 1]
                else:
                    tok_chunk[i, :] = self._tok[i]
                    pos_chunk[i, :] = self._pos[i]
            if self.paged:
                # lazy allocation tracks the prompt walk: each chunk
                # only maps the pages it is about to write
                for i in range(self.B):
                    if adv[i] > 0:
                        self._ensure_pages_to(
                            i, int(self._pos[i]) + int(adv[i]) - 1)
            tok_dev = jnp.asarray(tok_chunk)
            pos_dev = jnp.asarray(pos_chunk)
            aid_dev = jnp.asarray(self._aid)
            self._cache = self._prefill_fn(
                self.params, self._cache, tok_dev, pos_dev, aid_dev,
                self._ptab_arg())
            if self._draft_cache is not None and self._draft_synced:
                # keep the draft's KV in lockstep with the prompt walk
                # (while desynced, resync rebuilds prompts anyway)
                self._draft_cache = self._draft_sync_c(
                    self.draft_params, self._draft_cache, tok_dev,
                    pos_dev, aid_dev, self._ptab_arg())
            self.stats.inc("prefill_calls")
            self.stats.inc("prefill_tokens", int(adv.sum()))
            for i in range(self.B):
                if adv[i] > 0:
                    self._pos[i] += int(adv[i])
                    self._slots[i].n_consumed += int(adv[i])
                    self._tok[i] = self._prompt_buf[i, int(self._pos[i])]

    # ---- SLO preemption (lock held: admission-loop context) ----
    def _occupants(self) -> List[Tuple[int, str, int, bool]]:
        """Live slots as the ``(handle, slo, seq, shielded)`` tuples
        the shared eviction policy (`serving/slo.py`) consumes."""
        return [(j, s.slo, s.seq, s.shielded)
                for j, s in enumerate(self._slots) if s is not None]

    def _victim_for(self, cls: str) -> Optional[int]:
        """The slot to evict so a ``cls`` head can admit — the shared
        :func:`preemption_victim` policy (youngest lowest-class,
        shielded immune) over the live slots."""
        return preemption_victim(cls, self._occupants())

    def _evictable_for(self, cls: str) -> List[int]:
        """Every slot :meth:`_victim_for` could ever return for a
        ``cls`` head — the feasibility pre-check sums their
        reservations BEFORE committing any eviction (a preemption
        that cannot end in the head admitting would destroy the
        victims' progress for nothing; pre-SLO behavior just stalled
        in place with the lower-class work still running). Same
        predicate as victim selection BY CONSTRUCTION (both call
        :func:`evictable_occupants`), which is what guarantees the
        paged reclaim loop in :meth:`step` terminates in admission."""
        return [j for j, _s, _q in
                evictable_occupants(cls, self._occupants())]

    def _preempt_slot(self, j: int, by: str
                      ) -> Tuple[Any, int, int, str, str]:
        """Evict slot ``j`` mid-generation so a higher-class admission
        fits. Cheap under paged KV: the victim's pages + reservation
        return to the pool NOW; the victim becomes a front-of-class
        re-queued request whose prompt is its original prompt PLUS
        everything generated so far (the PR 7 forced-prefix shape), so
        on re-admission it re-ingests that prefix through chunked
        prefill and continues at the SAME absolute positions —
        token-exact in every decode mode (greedy argmax depends only
        on history; sampled draws are pure functions of (seed,
        position); speculation is greedy-lossless; int8-KV and
        multi-adapter ride the same cache math). The vacated KV rows
        are the standard unreachable-then-rewritten slot-reuse case.
        Returns the ``preempted`` span record."""
        slot = self._slots[j]
        gen = list(slot.generated)
        prompt = (np.concatenate([slot.prompt,
                                  np.asarray(gen, np.int32)])
                  if gen else slot.prompt)
        resumed = _Slot(slot.request_id, prompt,
                        slot.max_new - len(gen),
                        temperature=slot.temperature, top_k=slot.top_k,
                        top_p=slot.top_p, seed=slot.seed,
                        eos_id=slot.eos_id,
                        adapter_id=slot.adapter_id, slo=slot.slo,
                        seq=slot.seq, prior=slot.prior + gen)
        resumed.n_streamed = slot.n_streamed
        resumed.first_tokened = slot.first_tokened
        resumed.shielded = slot.shielded
        self._slots[j] = None
        self._tok[j] = 0
        self._pos[j] = 0  # fresh occupant restarts at position 0
        self._prompt_len[j] = 1
        self._stop_pos[j] = 0
        if self.paged:
            self._release_slot_pages(j, have_lock=True)
        self._cq.push(resumed.slo, resumed, front=True)
        self.stats.inc("preemptions")
        return (slot.request_id, j, len(gen), slot.slo, by)

    def _seat_slot(self, i: int, slot: _Slot,
                   prefix_hits: Dict[int, Tuple[Dict[str, Any],
                                                List[int]]]) -> None:
        """Install a popped request into free slot ``i``: host mirrors,
        shared-prefix fast-forward, first lazy pages. Lock held."""
        self._slots[i] = slot
        self._tok[i] = slot.prompt[0]
        self._pos[i] = 0
        self._prompt_buf[i, :] = 0
        self._prompt_buf[i, :len(slot.prompt)] = slot.prompt
        self._prompt_len[i] = len(slot.prompt)
        pre = self._prefixes.get(slot.adapter_id)
        if (pre is not None and len(slot.prompt) > pre["len"]
                and np.array_equal(slot.prompt[:pre["len"]],
                                   pre["ids"])):
            # shared-prefix hit: skip its prefill — the KV copy makes
            # positions 0..plen-1 as if prefilled, and the prompt walk
            # resumes at plen
            prefix_hits.setdefault(
                slot.adapter_id, (pre, []))[1].append(i)
            self._pos[i] = pre["len"]
            slot.n_consumed = pre["len"]
            self._tok[i] = slot.prompt[pre["len"]]
        # finish once pos reaches plen - 1 + max_new (the step at
        # input position p emits a GENERATED token iff p >= plen - 1)
        self._stop_pos[i] = min(
            len(slot.prompt) - 1 + slot.max_new, self.L)
        self._temp[i] = slot.temperature
        self._topk[i] = slot.top_k
        self._topp[i] = slot.top_p
        self._seed[i] = np.int32(slot.seed & 0x7FFFFFFF)
        self._aid[i] = slot.adapter_id
        if self.paged:
            # map the pages the slot starts on: position 0, or the
            # whole prefix span for a hit (install scatters into them
            # before the next call)
            self._ensure_pages_to(i, int(self._pos[i]))

    # ---- the loop body ----
    def step(self) -> int:
        """Admit queued requests into free slots, run K fused compiled
        steps for every live slot, harvest completions. Returns live
        count (at admission time)."""
        admitted_info: List[Tuple[Any, int, int, str]] = []
        preempted_info: List[Tuple[Any, int, int, str, str]] = []
        with self._lock:
            admitted = False
            # rows grouped by adapter id with the SNAPSHOT each matched
            # (one install per distinct snapshot; register_prefix is
            # documented as not concurrent with step, so within one
            # admission an adapter maps to exactly one snapshot)
            prefix_hits: Dict[int, Tuple[Dict[str, Any], List[int]]] = {}
            while True:
                nxt = self._cq.peek()
                if nxt is None:
                    break
                cls, head = nxt
                i = next((j for j in range(self.B)
                          if self._slots[j] is None), None)
                # feasibility BEFORE any eviction: admission is
                # bounded by slots AND (paged) the page pool — the
                # head admits only if its worst case (prompt +
                # max_new + spec margin — its ACTUAL size, never
                # max_len) fits what is free plus what eviction could
                # reclaim from strictly-lower-class, non-shielded
                # occupants. If even that is insufficient, STALL
                # WITHOUT evicting: destroying a victim's progress
                # while the head still cannot admit would be pure
                # loss (backpressure keeps FIFO fairness — smaller
                # latecomers never starve the head; completions free
                # reservations).
                victims = self._evictable_for(cls)
                if i is None and not victims:
                    break
                n_res = 0
                if self.paged:
                    n_res = self._pages_for(
                        min(len(head.prompt) - 1 + head.max_new,
                            self.L))
                    avail = self.n_pages - 1 - self._res_total
                    reclaim = sum(int(self._n_res[j]) for j in victims)
                    if avail + reclaim < n_res:
                        self.stats.inc("admission_stalls")
                        break
                if i is None:
                    # every slot occupied: evict the youngest
                    # lowest-class occupant (pages return NOW — cheap
                    # under paged KV; the victim resumes token-exact
                    # later from its re-queued prefix)
                    i = self._victim_for(cls)
                    preempted_info.append(self._preempt_slot(i, cls))
                if self.paged:
                    while self._res_total + n_res > self.n_pages - 1:
                        # guaranteed to terminate in admission by the
                        # feasibility check above
                        j = self._victim_for(cls)
                        preempted_info.append(
                            self._preempt_slot(j, cls))
                    self._n_res[i] = n_res
                    self._res_total += n_res
                # pop() == the peeked head: nothing ran between (a
                # preemption only pushes into strictly LOWER classes,
                # whose skip counters are unchanged)
                _, slot = self._cq.pop()
                if self._cq.last_pop_promoted:
                    slot.shielded = True  # aging fired: this slot may
                    #                       not be preempted in turn
                self._seat_slot(i, slot, prefix_hits)
                admitted = True
                admitted_info.append((slot.request_id, i,
                                      len(slot.prompt), slot.slo,
                                      bool(slot.prior)))
            depths = self._cq.depths()
            self.stats.set("slo_aged_promotions", self._cq.promotions)
            live = [i for i in range(self.B) if self._slots[i] is not None]
            self.stats.max_set("max_concurrent", len(live))
        for c, d in depths.items():
            self.stats.set(f"queued_{c}", d)
        # span emission OUTSIDE the engine lock: the sink may take its
        # own locks (trace buffer, histograms) and must not nest ours
        for rid, row, n_gen, vslo, by in preempted_info:
            self._span("preempted", rid, slot=row, tokens=n_gen,
                       slo=vslo, by=by)
        for rid, row, plen, cls, resumed in admitted_info:
            # `resumed` marks a preempt-resume RE-admission: observers
            # must not treat it as a fresh queue-wait sample (the gap
            # since submit includes the victim's pre-preemption
            # service time, not backlog)
            self._span("admitted", rid, slot=row, prompt_tokens=plen,
                       slo=cls, resumed=resumed)
        if not live:
            return 0
        for pre, rows in prefix_hits.values():
            # the snapshot each row matched against, NOT a fresh
            # self._prefixes lookup: a concurrent register_prefix must
            # not swap the tree under rows whose positions were
            # advanced by pre["len"]
            self._install_prefix(rows, pre)
        if admitted and self._prefill_fn is not None:
            self._chunked_prefill()
            for rid, row, plen, cls, resumed in admitted_info:
                self._span("prefill", rid, prompt_tokens=plen)
        if admitted or self._prompt_dev is None:
            # refresh the device-resident prompts only when they changed
            self._prompt_dev = jnp.asarray(self._prompt_buf)

        any_sampling = bool(any(
            self._slots[i] is not None and self._slots[i].temperature > 0
            for i in range(self.B)))
        # speculative path: all live slots greedy, past their prompts,
        # room for a full draft window in the cache, and recent
        # acceptance above break-even (or a periodic re-probe) —
        # otherwise this fused call runs the plain scan (the paths
        # interleave freely call-to-call; both emit exact argmax tokens)
        if (self._verify_fn is not None and not any_sampling
                and (self._spec_ema >= self._spec_floor
                     or self._spec_idle >= SPEC_REPROBE_CALLS)
                and all(self._pos[i] >= len(self._slots[i].prompt) - 1
                        and int(self._pos[i]) + self.spec_k <= self.L
                        for i in live)):
            return self._speculative_step(live)
        if self._verify_fn is not None:
            self._spec_idle += 1
        if self.paged:
            for i in live:
                # the fused scan writes positions pos..pos+K-1, frozen
                # at stop_pos-1: map exactly that window's pages
                self._ensure_pages_to(i, min(
                    int(self._pos[i]) + self.K,
                    int(self._stop_pos[i])) - 1)
        self._cache, emitted = self._step_fns[any_sampling](
            self.params, self._cache, jnp.asarray(self._tok),
            jnp.asarray(self._pos), self._prompt_dev,
            jnp.asarray(self._prompt_len), jnp.asarray(self._stop_pos),
            jnp.asarray(self._temp), jnp.asarray(self._topk),
            jnp.asarray(self._topp), jnp.asarray(self._seed),
            jnp.asarray(self._aid), self._ptab_arg())
        emitted = np.asarray(emitted)  # (K, B) — the per-token sync
        self.stats.inc("steps", self.K)
        if self._draft_cache is not None:
            if not any_sampling and (
                    self._spec_ema >= self._spec_floor
                    or self._spec_idle >= SPEC_REPROBE_CALLS - 1):
                if not self._draft_synced:
                    self._resync_draft()
                self._mirror_scan_onto_draft(emitted)
            else:
                # speculation can't pay off right now (gate off, or
                # sampling slots block the all-greedy precondition):
                # skip the per-scan mirror — a draft engine must not be
                # slower than no draft — and let the next re-probe
                # rebuild the cache from accepted contexts
                self._draft_synced = False

        finished: List[Tuple[Any, List[int]]] = []
        for i in live:
            slot = self._slots[i]
            plen = len(slot.prompt)
            pos0 = int(self._pos[i])
            # steps this slot actually took inside the fused program
            # (slots that hit their stop mid-scan idle for the rest)
            n_real = max(0, min(self.K, int(self._stop_pos[i]) - pos0,
                                self.L - pos0))
            eos_hit = False
            n0 = len(slot.generated)
            for j in range(n_real):
                if pos0 + j >= plen - 1:  # emission at a generated pos
                    t = int(emitted[j, i])
                    if slot.eos_id is not None and t == slot.eos_id:
                        # EOS ends the request; drop it and whatever the
                        # fused call computed past it
                        eos_hit = True
                        break
                    slot.generated.append(t)
            n1 = len(slot.generated)
            if n1 > n0:
                self.stats.inc("tokens_generated", n1 - n0)
                self._mark_progress(slot, n0, n1)
            slot.n_consumed += n_real
            self._pos[i] = pos0 + n_real
            if (eos_hit or len(slot.generated) >= slot.max_new
                    or int(self._pos[i]) >= self.L):
                # prior + generated: a preempt-resumed request replies
                # with its FULL output (the re-ingested prefix counts)
                finished.append((slot.request_id,
                                 slot.prior + slot.generated))
                self._slots[i] = None
                self._tok[i] = 0
                self._pos[i] = 0  # fresh occupant restarts at position 0
                self._prompt_len[i] = 1
                self._stop_pos[i] = 0
                if self.paged:  # pages (and the reservation) free NOW,
                    self._release_slot_pages(i)  # not at slot reuse
            else:
                # reconstruct the next input host-side (mirrors the
                # on-device selection, so the next fused call continues
                # seamlessly)
                self._tok[i] = (slot.prompt[slot.n_consumed]
                                if slot.n_consumed < plen
                                else slot.generated[-1])
        if finished:
            with self._lock:
                self._done.extend(finished)
                self.stats.inc("requests_done", len(finished))
            for rid, toks in finished:
                self._span("done", rid, tokens=len(toks))
        return len(live)

    def _mark_progress(self, slot: "_Slot", n0: int, n1: int) -> None:
        """first_token / periodic decode_mark spans for a slot that
        grew from ``n0`` to ``n1`` generated tokens this call. Pure
        integer math when no sink is wired."""
        if self.span_sink is None:
            return
        if not slot.first_tokened:
            # flag, not n0 == 0: a preempt-resumed slot restarts its
            # generated list at 0 but its stream already first-tokened
            slot.first_tokened = True
            self._span("first_token", slot.request_id)
        if n0 // SPAN_DECODE_MARK_EVERY != n1 // SPAN_DECODE_MARK_EVERY:
            self._span("decode_mark", slot.request_id, tokens=n1)

    def _resync_draft(self) -> None:
        """Rebuild the draft cache from every live slot's ACCEPTED
        context (prompt + generated, positions 0..pos-1). Runs when a
        re-probe follows a gated-off stretch during which scan mirrors
        were skipped — a bounded number of K-chunk passes instead of a
        mirror on every gated scan."""
        self._draft_cache = self.draft_module.init(
            jax.random.PRNGKey(0), jnp.zeros((self.B, 1), jnp.int32),
            decode=True)["cache"]
        ctxs = {}
        maxp = 0
        for i in range(self.B):
            s = self._slots[i]
            if s is None:
                continue
            ctx = np.concatenate(
                [s.prompt, np.asarray(s.generated, np.int32)])
            ctxs[i] = ctx[:int(self._pos[i])]
            maxp = max(maxp, len(ctxs[i]))
        for c0 in range(0, maxp, self.K):
            tok_m = np.zeros((self.B, self.K), np.int32)
            pos_m = np.zeros((self.B, self.K), np.int32)
            for i in range(self.B):
                ctx = ctxs.get(i)
                if ctx is None or len(ctx) <= c0:
                    # nothing (left) for this lane: idempotent rewrite
                    # of its current token at its current position
                    tok_m[i, :] = self._tok[i]
                    pos_m[i, :] = self._pos[i]
                    continue
                n = min(self.K, len(ctx) - c0)
                tok_m[i, :n] = ctx[c0:c0 + n]
                pos_m[i, :n] = np.arange(c0, c0 + n)
                tok_m[i, n:] = tok_m[i, n - 1]
                pos_m[i, n:] = pos_m[i, n - 1]
            self._draft_cache = self._draft_sync_k(
                self.draft_params, self._draft_cache,
                jnp.asarray(tok_m), jnp.asarray(pos_m),
                jnp.asarray(self._aid), self._ptab_arg())
        self._draft_synced = True
        self.stats.inc("draft_resyncs")

    def _mirror_scan_onto_draft(self, emitted: np.ndarray) -> None:
        """Write the fused scan's ACTUALLY-CONSUMED inputs into the
        draft cache (one multi-token KV pass) so the draft stays
        token-for-token synced with the target through prompts,
        generation, and mixed admission — the invariant draft-model
        speculation relies on. Idle lanes re-write their current token
        at their current position (idempotent)."""
        tok_m = np.empty((self.B, self.K), np.int32)
        pos_m = np.empty((self.B, self.K), np.int32)
        for i in range(self.B):
            s = self._slots[i]
            p0 = int(self._pos[i])
            cur = int(self._tok[i])
            if s is None:
                tok_m[i, :] = cur
                pos_m[i, :] = p0
                continue
            plen = len(s.prompt)
            n_real = max(0, min(self.K, int(self._stop_pos[i]) - p0,
                                self.L - p0))
            for j in range(self.K):
                if j < n_real:
                    p = p0 + j
                    if j == 0:
                        t = cur
                    elif p < plen:
                        t = int(s.prompt[p])
                    else:  # generated region: the previous step's token
                        t = int(emitted[j - 1, i])
                    tok_m[i, j], pos_m[i, j] = t, p
                else:  # idle remainder: idempotent rewrite of the last
                    tok_m[i, j] = tok_m[i, j - 1] if j else cur
                    pos_m[i, j] = pos_m[i, j - 1] if j else p0
        self._draft_cache = self._draft_sync_k(
            self.draft_params, self._draft_cache, jnp.asarray(tok_m),
            jnp.asarray(pos_m), jnp.asarray(self._aid),
            self._ptab_arg())

    def _speculative_step(self, live: List[int]) -> int:
        """One verify call: host-drafted continuations for every live
        slot ride through a single multi-token cache step; each slot
        emits its accepted prefix plus the model's own token at the
        first mismatch (1..spec_k tokens). Rejected drafts leave stale
        KV rows ABOVE the slot's new position — unreachable by the
        position mask, and rewritten in place when generation reaches
        them (the admission-reuse invariant already relies on this)."""
        k = self.spec_k
        if self._draft_cache is not None:
            if not self._draft_synced:  # re-probe after a gated-off
                self._resync_draft()    # stretch with skipped mirrors
            # draft phase: k-1 fused greedy steps on the DRAFT model
            # (argmax feedback), advancing its synced cache; then the
            # verify mirror writes the window's inputs [tok, drafts]
            # so the final row exists for fully-accepted windows
            self._draft_cache, d_emit = self._draft_scan(
                self.draft_params, self._draft_cache,
                jnp.asarray(self._tok), jnp.asarray(self._pos),
                self._prompt_dev, jnp.asarray(self._prompt_len),
                jnp.asarray(self._stop_pos), jnp.asarray(self._temp),
                jnp.asarray(self._topk), jnp.asarray(self._topp),
                jnp.asarray(self._seed), jnp.asarray(self._aid),
                self._ptab_arg())
            drafts = np.asarray(d_emit).T.astype(np.int32)  # (B, k-1)
            offs = np.arange(k, dtype=np.int32)[None, :]
            self._draft_cache = self._draft_sync_v(
                self.draft_params, self._draft_cache,
                jnp.asarray(np.concatenate(
                    [self._tok[:, None], drafts], axis=1)),
                jnp.asarray(self._pos[:, None] + offs),
                jnp.asarray(self._aid), self._ptab_arg())
            self.stats.inc("spec_draft_model_calls")
        else:
            drafts = np.zeros((self.B, k - 1), np.int32)
            for i in live:
                s = self._slots[i]
                ctx = np.concatenate(
                    [s.prompt, np.asarray(s.generated, np.int32)])
                drafts[i] = _ngram_draft(ctx, k - 1)
        if self.paged:
            for i in live:
                # the verify window writes positions pos..pos+k-1
                # (gated above to fit the cache); its pages must exist
                # even for drafts that end up rejected — the standard
                # unreachable-then-rewritten rows, inside reservation
                self._ensure_pages_to(i, min(
                    int(self._pos[i]) + k - 1, self.L - 1))
        self._cache, g, n_emit = self._verify_fn(
            self.params, self._cache, jnp.asarray(self._tok),
            jnp.asarray(self._pos), jnp.asarray(drafts),
            jnp.asarray(self._stop_pos), jnp.asarray(self._aid),
            self._ptab_arg())
        g = np.asarray(g)            # (B, k) model argmax per position
        n_emit = np.asarray(n_emit)  # (B,) 1 + accepted draft prefix
        self.stats.inc("steps")
        self.stats.inc("spec_calls")
        self._spec_idle = 0
        self._spec_ema = (SPEC_EMA_DECAY * self._spec_ema
                          + (1 - SPEC_EMA_DECAY)
                          * float(np.mean(n_emit[live])))

        finished: List[Tuple[Any, List[int]]] = []
        for i in live:
            slot = self._slots[i]
            pos0 = int(self._pos[i])
            take = max(1, min(int(n_emit[i]),
                              int(self._stop_pos[i]) - pos0,
                              self.L - pos0))
            toks = [int(t) for t in g[i, :take]]
            eos_hit = slot.eos_id is not None and slot.eos_id in toks
            if eos_hit:  # drop the EOS and anything verified past it
                toks = toks[:toks.index(slot.eos_id)]
            n0 = len(slot.generated)
            slot.generated.extend(toks)
            slot.n_consumed += take
            self._pos[i] = pos0 + take
            if toks:
                self.stats.inc("tokens_generated", len(toks))
                self._mark_progress(slot, n0, len(slot.generated))
            self.stats.inc("spec_drafted", k - 1)
            self.stats.inc("spec_accepted", take - 1)
            if (eos_hit or len(slot.generated) >= slot.max_new
                    or int(self._pos[i]) >= self.L):
                finished.append((slot.request_id,
                                 slot.prior + slot.generated))
                self._slots[i] = None
                self._tok[i] = 0
                self._pos[i] = 0
                self._prompt_len[i] = 1
                self._stop_pos[i] = 0
                if self.paged:
                    self._release_slot_pages(i)
            else:
                self._tok[i] = slot.generated[-1]
        if finished:
            with self._lock:
                self._done.extend(finished)
                self.stats.inc("requests_done", len(finished))
            for rid, toks in finished:
                self._span("done", rid, tokens=len(toks))
        return len(live)


def _ngram_draft(context: np.ndarray, k: int, max_n: int = 3) -> np.ndarray:
    """Prompt-lookup drafting: find the longest (≤ ``max_n``) suffix
    n-gram of ``context`` with an earlier occurrence and propose the
    ``k`` tokens that followed its most recent match; repeat-last when
    nothing matches. Pure host-side numpy — drafting costs no device
    time, and a bad draft costs nothing but its rejected verify lanes."""
    ctx = np.asarray(context, np.int32).ravel()
    n_ctx = len(ctx)
    for n in range(min(max_n, n_ctx - 1), 0, -1):
        suffix = ctx[n_ctx - n:]
        # windows over ctx[:-1]: every start whose n-gram ends before
        # the suffix's own final token
        windows = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
        hits = np.nonzero(np.all(windows == suffix, axis=1))[0]
        if len(hits):
            j = int(hits[-1]) + n  # continuation of the latest match
            cont = ctx[j:j + k]
            if len(cont) < k:
                cont = np.concatenate(
                    [cont, np.full(k - len(cont), ctx[-1], np.int32)])
            return cont.astype(np.int32)
    return np.full(k, ctx[-1], np.int32)


def _select_next(logits, temp, top_k, top_p, seed, pos):
    """Per-slot token selection on device: greedy when ``temp <= 0``,
    else temperature-scaled categorical over the top-k/top-p-filtered
    distribution. Both filters reduce to a per-row LOGIT THRESHOLD on
    the descending sort (k-th largest for top-k; the smallest logit of
    the minimal nucleus for top-p), so one sort serves both and the
    masked sample needs no gather back through sort order. The PRNG key
    is ``fold_in(fold_in(base, seed), position)`` — a pure function of
    (seed, position), so draws are reproducible under any batch
    composition, slot placement, or step fusion."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, -1).astype(jnp.int32)
    lg = logits / jnp.maximum(temp, 1e-6)[:, None]
    sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]  # descending
    kk = jnp.clip(jnp.where(top_k <= 0, v, top_k), 1, v)
    k_thresh = jnp.take_along_axis(
        sorted_lg, (kk - 1)[:, None].astype(jnp.int32), axis=-1)
    probs = jax.nn.softmax(sorted_lg, -1)
    cum = jnp.cumsum(probs, -1)
    # keep the minimal prefix whose mass reaches top_p (the first token
    # is always kept: its "mass before" is 0 < top_p)
    keep = (cum - probs) < jnp.maximum(top_p, 1e-6)[:, None]
    p_thresh = jnp.min(jnp.where(keep, sorted_lg, jnp.inf), -1,
                       keepdims=True)
    masked = jnp.where(lg >= jnp.maximum(k_thresh, p_thresh), lg, -1e30)
    base = jax.random.PRNGKey(0)
    keys = jax.vmap(lambda s, p: jax.random.fold_in(
        jax.random.fold_in(base, s), p))(seed, pos)
    sampled = jax.vmap(jax.random.categorical)(keys,
                                               masked).astype(jnp.int32)
    return jnp.where(temp <= 0.0, greedy, sampled)


@functools.lru_cache(maxsize=8)
def _make_step(module: Any, n_slots: int, k: int,
               sampling: bool) -> Callable:
    """K fused decode steps over all slots (cache donated in-place).

    On-device input selection between steps: while a slot's next
    position is still inside its prompt, the next input is the next
    prompt token (device-resident prompt buffer); afterwards it is the
    slot's own sampled/greedy token (``_select_next`` when ``sampling``,
    plain argmax otherwise — the greedy program never compiles the
    sampler's per-token vocab sort). Slots whose next position reaches
    ``stop_pos`` freeze (their tok/pos stop advancing) so a finished
    slot idles harmlessly for the remainder of the scan.

    Multi-adapter modules additionally consume the per-slot ``aid``
    operand (which stacked fine-tune each row decodes under); paged-KV
    modules the per-slot ``ptab`` page tables (a tiny ignored constant
    otherwise — one signature for both layouts)."""
    multi = int(getattr(module, "n_adapters", 0) or 0) > 0
    paged = int(getattr(module, "kv_page_size", 0) or 0) > 0

    @functools.partial(jax.jit, donate_argnums=(1,))
    def step_fn(params, cache, tok, pos, prompt_buf, prompt_len, stop_pos,
                temp, top_k, top_p, seed, aid, ptab):
        rows = jnp.arange(n_slots)

        def body(carry, _):
            cache, tok, pos = carry
            logits, muts = module.apply(
                {"params": params, "cache": cache}, tok[:, None],
                positions=pos[:, None], decode=True, mutable=["cache"],
                **({"adapter_ids": aid} if multi else {}),
                **({"page_tables": ptab} if paged else {}))
            lg = logits[:, -1].astype(jnp.float32)
            if sampling:
                nxt = _select_next(lg, temp, top_k, top_p, seed, pos)
            else:
                nxt = jnp.argmax(lg, -1).astype(jnp.int32)
            new_pos = pos + 1
            is_prefill = new_pos < prompt_len
            nxt_prompt = prompt_buf[
                rows, jnp.minimum(new_pos, prompt_buf.shape[1] - 1)]
            nxt_input = jnp.where(is_prefill, nxt_prompt, nxt)
            active = new_pos < stop_pos
            tok2 = jnp.where(active, nxt_input, tok)
            pos2 = jnp.where(active, new_pos, pos)
            return (muts["cache"], tok2, pos2), nxt

        (cache, tok, pos), emitted = jax.lax.scan(
            body, (cache, tok, pos), None, length=k)
        return cache, emitted  # (K, n_slots)

    return step_fn


@functools.lru_cache(maxsize=8)
def _make_verify(module: Any, n_slots: int, k: int) -> Callable:
    """One speculative verify step: feed each slot's current token plus
    its k-1 drafted continuations at positions pos..pos+k-1 through the
    decode-cache path (the chunked-prefill machinery — KV for the whole
    window is written before attention, and each query only sees keys
    at-or-before its own position). ``g[:, j]`` is the model's argmax
    AFTER input j, so draft j+1 is correct iff it equals ``g[:, j]``;
    ``n_emit`` = 1 + the length of the all-correct draft prefix — every
    emitted token is conditioned only on accepted history, which is what
    makes greedy speculation lossless. Free/finished slots re-feed their
    current token at their current position (an idempotent rewrite)."""

    multi = int(getattr(module, "n_adapters", 0) or 0) > 0
    paged = int(getattr(module, "kv_page_size", 0) or 0) > 0

    @functools.partial(jax.jit, donate_argnums=(1,))
    def verify_fn(params, cache, tok, pos, drafts, stop_pos, aid, ptab):
        active = (pos < stop_pos)[:, None]
        offs = jnp.arange(k)[None, :]
        seq = jnp.concatenate([tok[:, None], drafts], axis=1)
        seq = jnp.where(active, seq, tok[:, None])
        positions = jnp.where(active, pos[:, None] + offs, pos[:, None])
        logits, muts = module.apply(
            {"params": params, "cache": cache}, seq,
            positions=positions, decode=True, mutable=["cache"],
            **({"adapter_ids": aid} if multi else {}),
            **({"page_tables": ptab} if paged else {}))
        g = jnp.argmax(logits.astype(jnp.float32), -1).astype(jnp.int32)
        ok = jnp.cumprod((drafts == g[:, :-1]).astype(jnp.int32), axis=1)
        n_emit = 1 + jnp.sum(ok, axis=1).astype(jnp.int32)
        return muts["cache"], g, n_emit

    return verify_fn


@functools.lru_cache(maxsize=32)
def _make_prefix_install(plen: int) -> Callable:
    """Scatter a trimmed prefix snapshot into slot rows. Cached by
    prefix length so N same-text registrations (one per adapter in a
    multi-tenant boot) share ONE compiled program — only the forward
    prefill execution is genuinely per-adapter."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def install(cache, pre, rws):
        return jax.tree_util.tree_map(
            lambda c, p: c.at[rws, :plen].set(
                p[:, :plen].astype(c.dtype)), cache, pre)

    return install


@functools.lru_cache(maxsize=32)
def _make_paged_prefix_install(plen: int, page_size: int) -> Callable:
    """Paged-engine twin of :func:`_make_prefix_install`: scatter a
    (1, plen, …) contiguous snapshot into the hit slots' PAGES —
    ``tabs`` is the (n_rows, n_tables) page-table slice of exactly the
    rows being installed, whose prefix pages the engine allocated at
    admission. Cached by (length, page size) like its contiguous twin."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def install(cache, pre, tabs):
        pos = jnp.arange(plen)
        pg = tabs[:, pos // page_size]   # (n_rows, plen) pool pages
        off = pos % page_size            # (plen,) in-page offsets

        def put(c, p):
            vals = jnp.broadcast_to(
                p[:, :plen].astype(c.dtype),
                (tabs.shape[0], plen) + p.shape[2:])
            return c.at[pg, off].set(vals)

        return jax.tree_util.tree_map(put, cache, pre)

    return install


@functools.lru_cache(maxsize=8)
def _make_prefill(module: Any, n_slots: int, chunk: int) -> Callable:
    """One C-token prefill call: feed (B, C) tokens at their per-slot
    positions through the decode-cache path. The lm_head output is
    discarded (prefill emits nothing), so XLA dead-code-eliminates the
    (B, C, vocab) projection — the call is pure KV-cache population at
    matmul (not matvec) arithmetic intensity."""
    multi = int(getattr(module, "n_adapters", 0) or 0) > 0
    paged = int(getattr(module, "kv_page_size", 0) or 0) > 0

    @functools.partial(jax.jit, donate_argnums=(1,))
    def prefill_fn(params, cache, tok_chunk, pos_chunk, aid, ptab):
        _, muts = module.apply(
            {"params": params, "cache": cache}, tok_chunk,
            positions=pos_chunk, decode=True, mutable=["cache"],
            **({"adapter_ids": aid} if multi else {}),
            **({"page_tables": ptab} if paged else {}))
        return muts["cache"]

    return prefill_fn


class TextDecodeEngine:
    """Text-level wrapper: encode prompts, detokenize completions.

    ``encode(text) -> 1-D int32 ids`` and ``decode(ids) -> text`` come
    from the owning model template (see ``LlamaLoRA.make_decode_engine``).
    """

    #: the inference worker checks this before forwarding a failover
    #: request's ``forced_prefix`` (duck-typed user engines without the
    #: kwarg must get a structured rejection, not a TypeError that
    #: kills the serve thread)
    supports_resume = True
    #: ditto for the ``slo`` admission-class kwarg: the worker only
    #: forwards it to engines that declare the capability (a duck-typed
    #: user engine must degrade to classless FIFO, not TypeError)
    supports_slo = True

    def __init__(self, engine: DecodeEngine,
                 encode: Callable[[str], np.ndarray],
                 decode: Callable[[List[int]], str],
                 max_new: int = 8, resume_sep: str = " ") -> None:
        self.engine = engine
        self._encode = encode
        self._decode = decode
        self.max_new = int(max_new)
        #: text joint between a prompt and a forced resume prefix (and
        #: between the prefix and the continuation decode): " " matches
        #: both tokenizer families — the hash tokenizer splits/joins on
        #: whitespace exactly, and the byte-BPE detok lstrips the
        #: leading space its first generated token usually carries
        self._sep = resume_sep
        self._stream_sent: Dict[Any, str] = {}  # rid -> text delivered
        #: rid -> forced resume prefix (failover re-submissions): the
        #: already-delivered text the engine re-ingests as prompt but
        #: which deltas/finals must present as generated output
        self._forced: Dict[Any, str] = {}
        #: resume requests whose prefix already covered the whole token
        #: budget: completed without touching the engine, surfaced on
        #: the next poll()
        self._forced_done: List[Tuple[Any, str]] = []

    def submit(self, request_id: Any, text: str,
               max_new: Optional[int] = None, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0, seed: int = 0,
               eos_id: Optional[int] = None, adapter_id: int = 0,
               forced_prefix: str = "", slo: str = "") -> None:
        """``forced_prefix`` (streaming failover / client resume): text
        a previous worker already emitted for this request. It is
        re-ingested as part of the prompt (the engine's chunked-prefill
        path — prefix compute at matmul intensity, no decode steps),
        the token budget shrinks by the tokens it covers, and deltas /
        the final text present it as OUTPUT — the resumed stream
        continues exactly where the dead one stopped, without
        re-emitting or dropping text. Greedy continuations are
        token-exact whenever re-tokenizing prompt+prefix reproduces the
        original token boundaries (true for the whitespace tokenizer;
        byte-BPE may shift a boundary at the splice, in which case the
        predictor's replace/divergence machinery still keeps the client
        consistent)."""
        budget = self.max_new if max_new is None else int(max_new)
        if forced_prefix:
            full = text + self._sep + forced_prefix
            covered = max(0, len(self._encode(full))
                          - len(self._encode(text)))
            remaining = budget - covered
            if remaining <= 0:
                # the dead worker had already generated the whole
                # budget; only its final message was lost — complete
                # instantly with the prefix as the authoritative text
                self._forced_done.append((request_id,
                                          str(forced_prefix)))
                return
            self._forced[request_id] = str(forced_prefix)
            self._stream_sent[request_id] = str(forced_prefix)
            text, budget = full, remaining
        self.engine.submit(request_id, self._encode(text), budget,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, seed=seed, eos_id=eos_id,
                           adapter_id=adapter_id, slo=slo)

    def _full_text(self, rid: Any, ids: List[int]) -> str:
        """The request's cumulative OUTPUT text: decoded generated ids,
        preceded by the forced resume prefix when one is active."""
        text = self._decode(ids)
        base = self._forced.get(rid)
        if base is not None:
            text = base + (self._sep + text if text else "")
        return text

    def poll(self) -> List[Tuple[Any, str]]:
        done = [(rid, self._full_text(rid, ids))
                for rid, ids in self.engine.poll()]
        done.extend(self._forced_done)
        self._forced_done = []
        for rid, _ in done:  # a finished request stops streaming state
            self._stream_sent.pop(rid, None)
            self._forced.pop(rid, None)
        return done

    def poll_partial(self) -> List[Tuple[Any, str]]:
        """(request_id, new text) for live requests since the last call.

        Each event re-detokenizes the cumulative ids and emits the text
        suffix past what was already delivered — cumulative decoding is
        the only well-formed view under byte-level BPE (a token boundary
        may split a multi-byte character, so per-token decodes are not
        concatenation-safe). Trailing replacement characters (U+FFFD —
        an incomplete UTF-8 sequence whose remaining bytes are still
        being generated) are WITHHELD until a later decode resolves
        them: emitted text comes only from byte-complete prefixes, so
        the delivered stream is append-only and deltas concatenate
        correctly. Genuinely invalid bytes (never completed) surface in
        the final text instead. Suffix-empty events are dropped."""
        out: List[Tuple[Any, str]] = []
        for rid, ids in self.engine.poll_partial():
            text = self._full_text(rid, ids).rstrip("�")
            sent = self._stream_sent.get(rid, "")
            if len(text) > len(sent) and text.startswith(sent):
                out.append((rid, text[len(sent):]))
                self._stream_sent[rid] = text
        return out

    def register_prefix(self, text: str, adapter_id: int = 0) -> int:
        """Precompute KV for a shared prompt prefix (system prompt);
        see :meth:`DecodeEngine.register_prefix`. Call before serving
        traffic (not concurrently with ``step``)."""
        return self.engine.register_prefix(self._encode(text),
                                           adapter_id=adapter_id)

    def step(self) -> int:
        return self.engine.step()

    def reset(self) -> None:
        self._stream_sent.clear()
        self._forced.clear()
        self._forced_done.clear()
        self.engine.reset()

    def reset_stats(self) -> None:
        self.engine.reset_stats()

    @property
    def busy(self) -> bool:
        return self.engine.busy

    @property
    def stats(self) -> Dict[str, int]:
        return self.engine.stats

    def stats_snapshot(self) -> Dict[str, int]:
        return self.engine.stats_snapshot()

    @property
    def span_sink(self):
        return self.engine.span_sink

    @span_sink.setter
    def span_sink(self, sink) -> None:
        # request ids pass through submit untouched, so the token
        # engine's lifecycle events carry the caller's ids directly
        self.engine.span_sink = sink
