"""Query/prediction queues between the Predictor and inference workers.

Parity target: the reference's per-worker Redis lists (SURVEY.md §2
"Query/prediction queues", §3.3): the predictor pushes each query batch
onto every worker's query queue and gathers replies; workers block-pop,
predict, and push predictions back.

Two hubs, one interface: ``InProcQueueHub`` (threads in one process —
tests and the single-host fast path) and ``KVQueueHub`` (the native
``rafiki-kvd`` server — multi-process deployments). Replies land on a
per-query-id queue so the predictor can gather exactly the replicas it
scattered to, concurrently across outstanding queries.

Messages are msgpack-serialized pytrees (same codec as the ParamStore) so
query arrays cross process boundaries without JSON inflation.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, Optional

from ..store.param_store import params_from_bytes, params_to_bytes


def pack_message(msg: Dict[str, Any]) -> bytes:
    return params_to_bytes(msg)


def unpack_message(data: bytes) -> Dict[str, Any]:
    return params_from_bytes(data)


class QueueHub:
    """Scatter/gather data plane between one predictor and its workers."""

    def push_query(self, worker_id: str, data: bytes) -> None:
        raise NotImplementedError

    def pop_query(self, worker_id: str,
                  timeout: float) -> Optional[bytes]:
        raise NotImplementedError

    def push_prediction(self, query_id: str, data: bytes) -> None:
        raise NotImplementedError

    def pop_prediction(self, query_id: str,
                       timeout: float) -> Optional[bytes]:
        raise NotImplementedError

    def query_depth(self, worker_id: str) -> int:
        raise NotImplementedError


class InProcQueueHub(QueueHub):
    def __init__(self) -> None:
        self._queues: Dict[str, collections.deque] = \
            collections.defaultdict(collections.deque)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def _push(self, key: str, data: bytes) -> None:
        with self._cv:
            self._queues[key].append(data)
            self._cv.notify_all()

    def _pop(self, key: str, timeout: float) -> Optional[bytes]:
        with self._cv:
            ok = self._cv.wait_for(lambda: bool(self._queues.get(key)),
                                   timeout=timeout)
            if not ok:
                return None
            return self._queues[key].popleft()

    def push_query(self, worker_id: str, data: bytes) -> None:
        self._push(f"q:{worker_id}", data)

    def pop_query(self, worker_id: str, timeout: float) -> Optional[bytes]:
        return self._pop(f"q:{worker_id}", timeout)

    def push_prediction(self, query_id: str, data: bytes) -> None:
        self._push(f"p:{query_id}", data)

    def pop_prediction(self, query_id: str,
                       timeout: float) -> Optional[bytes]:
        return self._pop(f"p:{query_id}", timeout)

    def query_depth(self, worker_id: str) -> int:
        with self._lock:
            return len(self._queues.get(f"q:{worker_id}", ()))


class KVQueueHub(QueueHub):
    """Queues on the native kv server. Blocking pops hold a socket, so each
    hub keeps one client per calling thread (thread-local)."""

    def __init__(self, host: str, port: int) -> None:
        self._host, self._port = host, port
        self._tl = threading.local()

    def _client(self):
        from ..native.client import KVClient

        c = getattr(self._tl, "client", None)
        if c is None:
            c = KVClient(self._host, self._port)
            self._tl.client = c
        return c

    def push_query(self, worker_id: str, data: bytes) -> None:
        self._client().lpush(f"q:queries:{worker_id}", data)

    def pop_query(self, worker_id: str, timeout: float) -> Optional[bytes]:
        if timeout <= 0:  # non-blocking drain (BRPOP 0 means block forever)
            return self._client().rpop(f"q:queries:{worker_id}")
        got = self._client().brpop(f"q:queries:{worker_id}", timeout)
        return None if got is None else got[1]

    def push_prediction(self, query_id: str, data: bytes) -> None:
        self._client().lpush(f"q:preds:{query_id}", data)

    def pop_prediction(self, query_id: str,
                       timeout: float) -> Optional[bytes]:
        if timeout <= 0:
            return self._client().rpop(f"q:preds:{query_id}")
        got = self._client().brpop(f"q:preds:{query_id}", timeout)
        return None if got is None else got[1]

    def query_depth(self, worker_id: str) -> int:
        return self._client().llen(f"q:queries:{worker_id}")
