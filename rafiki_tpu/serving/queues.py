"""Query/prediction queues between the Predictor and inference workers.

Parity target: the reference's per-worker Redis lists (SURVEY.md §2
"Query/prediction queues", §3.3): the predictor pushes each query batch
onto every worker's query queue and gathers replies; workers block-pop,
predict, and push predictions back.

Two hubs, one interface: ``InProcQueueHub`` (threads in one process —
tests and the single-host fast path) and ``KVQueueHub`` (the native
``rafiki-kvd`` server — multi-process deployments). Replies land on a
per-query-id queue so the predictor can gather exactly the replicas it
scattered to, concurrently across outstanding queries.

Messages are msgpack-serialized pytrees (same codec as the ParamStore) so
query arrays cross process boundaries without JSON inflation.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, Optional

from ..store.param_store import params_from_bytes, params_to_bytes


#: tolerance a worker adds to a query's deadline_ts before dropping it
#: as expired — covers predictor↔worker wall-clock skew (ADVICE r3).
#: Lives here (the shared data-plane module) because both sides size
#: against it: workers pad the drop test, the predictor pads reply-queue
#: TTLs so skew-window stragglers still get collected.
EXPIRY_SKEW_TOLERANCE_S = 3.0


def pack_message(msg: Dict[str, Any]) -> bytes:
    return params_to_bytes(msg)


def unpack_message(data: bytes) -> Dict[str, Any]:
    return params_from_bytes(data)


class QueueHub:
    """Scatter/gather data plane between one predictor and its workers."""

    def push_query(self, worker_id: str, data: bytes) -> None:
        raise NotImplementedError

    def pop_query(self, worker_id: str,
                  timeout: float) -> Optional[bytes]:
        raise NotImplementedError

    def push_prediction(self, query_id: str, data: bytes) -> None:
        raise NotImplementedError

    def pop_prediction(self, query_id: str,
                       timeout: float) -> Optional[bytes]:
        raise NotImplementedError

    def query_depth(self, worker_id: str) -> int:
        raise NotImplementedError

    def discard_prediction_queue(self, query_id: str) -> None:
        """Drop a query's reply queue after the gather finishes. Late
        replies (a worker answering after the deadline) would otherwise
        accumulate forever in the backing store."""
        raise NotImplementedError

    def arm_reply_ttl(self, query_id: str, ttl_s: float) -> None:
        """Condemn a query's reply queue ``ttl_s`` from now, armed at
        SCATTER time. Belt to discard's suspenders: a worker inside the
        expiry skew window may push a reply AFTER the gather discarded
        the queue, recreating it — the pre-armed TTL collects that
        straggler. Backends with their own sweep may no-op."""

    def put_worker_stats(self, worker_id: str, stats: Dict[str, Any]
                         ) -> None:
        """Workers publish their counters (dropped-expired queries,
        decode-engine stats) here; the predictor's /health aggregates
        them — the first diagnostic when 'the predictor only sees
        timeouts' (ADVICE r3: silent drops were invisible)."""
        raise NotImplementedError

    def get_worker_stats(self, worker_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def put_pool_members(self, pool_id: str,
                         members: Dict[str, Any]) -> None:
        """The control plane publishes a job's live worker-id set here
        (``{"workers": [...], "version": ...}``) whenever the pool
        changes — autoscale up/down, manual scale. The predictor polls
        it (rate-limited) and applies the diff to its breaker board +
        router table, so membership follows the worker set without a
        predictor rebuild. Deliberately durable (no TTL): membership is
        configuration, not liveness — health stays the breakers' job."""
        raise NotImplementedError

    def get_pool_members(self, pool_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    # ---- disaggregated prefill/decode: KV page shipments ----
    def push_kv(self, worker_id: str, data: bytes) -> None:
        """Ship a finished KV-page blob to ``worker_id``'s shipment
        queue (prefill-role worker → decode-role worker; see
        ``serving/kv_transfer.py``). A dedicated channel, not the
        query queue: the decode loop drains it non-blockingly between
        steps and a burst of multi-MB blobs must never delay control
        or query messages behind it."""
        raise NotImplementedError

    def pop_kv(self, worker_id: str, timeout: float) -> Optional[bytes]:
        raise NotImplementedError

    def kv_depth(self, worker_id: str) -> int:
        """Unconsumed shipments queued for ``worker_id`` (obs only)."""
        raise NotImplementedError

    # ---- cross-worker shared blobs (prefix snapshots) ----
    def put_blob(self, key: str, data: bytes) -> None:
        """Durable named blob (e.g. a job's shared-prefix KV snapshot,
        ``prefix:<pool>:<adapter>``): prefilled ONCE, imported by every
        replica instead of each re-running the prefill forward."""
        raise NotImplementedError

    def get_blob(self, key: str) -> Optional[bytes]:
        raise NotImplementedError


class _KeyQueue:
    """One deque + its OWN condvar. A shared hub-wide condition would
    notify_all() every waiter (workers blocked on queries, predictor
    threads blocked on unrelated replies) for every push — a thundering
    herd that measurably lost to the socket-based kv hub under
    multi-client load."""

    __slots__ = ("dq", "cv", "last_used", "waiters")

    def __init__(self) -> None:
        self.dq: collections.deque = collections.deque()
        self.cv = threading.Condition()
        self.last_used = 0.0
        self.waiters = 0  # parked poppers — sweeping their entry would
        #                   orphan them (a later push notifies a NEW obj)


#: reply queues are per-query-id and transient; entries idle this long
#: with nothing queued are swept (abandoned after a gather deadline)
_IDLE_TTL_S = 120.0
_SWEEP_EVERY = 1024  # hub ops between sweeps


class InProcQueueHub(QueueHub):
    def __init__(self) -> None:
        self._queues: Dict[str, _KeyQueue] = {}
        self._meta = threading.Lock()  # guards the key → queue dict
        self._ops = 0
        self._stats: Dict[str, Dict[str, Any]] = {}  # worker counters
        self._pools: Dict[str, Dict[str, Any]] = {}  # pool memberships
        self._blobs: Dict[str, bytes] = {}  # shared prefix snapshots
        #: armed reply-queue TTLs (key → monotonic deadline): unlike the
        #: idle sweep, an armed TTL fires even while late pushes keep
        #: refreshing last_used (an abandoned STREAM's worker keeps
        #: pushing deltas long after the client went away)
        self._ttls: Dict[str, float] = {}

    def _get(self, key: str, *, as_waiter: bool = False) -> _KeyQueue:
        import time

        with self._meta:
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = _KeyQueue()
            if as_waiter:
                # registered BEFORE _meta is released: waiters is read by
                # discard/sweep under _meta, so a popper that has fetched
                # the queue can never be invisible to them (the window
                # between fetch and a later increment under q.cv orphaned
                # poppers on deleted entries — ADVICE r3)
                q.waiters += 1
            q.last_used = time.monotonic()
            self._ops += 1
            if self._ops % _SWEEP_EVERY == 0:
                now = q.last_used  # just-refreshed monotonic time
                cutoff = now - _IDLE_TTL_S
                dead = [k for k, v in self._queues.items()
                        if not v.waiters and v.last_used < cutoff
                        # reply queues (p:*) expire even NON-empty: a
                        # late push after discard recreates the entry
                        # and nothing would ever pop it
                        and (not v.dq or k.startswith("p:"))]
                for k in dead:  # e.g. replies that arrived after their
                    del self._queues[k]  # query's gather deadline
                # armed TTLs fire regardless of last_used (a worker
                # still streaming deltas into an abandoned queue keeps
                # it perpetually 'fresh' for the idle sweep above)
                for k in [k for k, dl in self._ttls.items() if dl < now]:
                    del self._ttls[k]
                    v = self._queues.get(k)
                    if v is not None and not v.waiters:
                        del self._queues[k]
            return q

    def _push(self, key: str, data: bytes) -> None:
        q = self._get(key)
        with q.cv:
            q.dq.append(data)
            q.cv.notify()

    def _pop(self, key: str, timeout: float) -> Optional[bytes]:
        q = self._get(key, as_waiter=True)
        try:
            with q.cv:
                ok = q.cv.wait_for(lambda: bool(q.dq), timeout=timeout)
                if not ok:
                    return None
                return q.dq.popleft()
        finally:
            with self._meta:  # all waiters transitions happen under _meta
                q.waiters -= 1

    def push_query(self, worker_id: str, data: bytes) -> None:
        self._push(f"q:{worker_id}", data)

    def pop_query(self, worker_id: str, timeout: float) -> Optional[bytes]:
        return self._pop(f"q:{worker_id}", timeout)

    def push_prediction(self, query_id: str, data: bytes) -> None:
        self._push(f"p:{query_id}", data)

    def pop_prediction(self, query_id: str,
                       timeout: float) -> Optional[bytes]:
        return self._pop(f"p:{query_id}", timeout)

    def query_depth(self, worker_id: str) -> int:
        with self._meta:
            q = self._queues.get(f"q:{worker_id}")
        return len(q.dq) if q is not None else 0

    def arm_reply_ttl(self, query_id: str, ttl_s: float) -> None:
        import time

        with self._meta:
            self._ttls[f"p:{query_id}"] = time.monotonic() + float(ttl_s)

    def discard_prediction_queue(self, query_id: str) -> None:
        with self._meta:
            q = self._queues.get(f"p:{query_id}")
            if q is not None and not q.waiters:
                del self._queues[f"p:{query_id}"]

    def put_worker_stats(self, worker_id: str, stats) -> None:
        with self._meta:
            self._stats[worker_id] = dict(stats)

    def get_worker_stats(self, worker_id: str):
        with self._meta:
            return self._stats.get(worker_id)

    def put_pool_members(self, pool_id: str, members) -> None:
        with self._meta:
            self._pools[pool_id] = dict(members)

    def get_pool_members(self, pool_id: str):
        with self._meta:
            return self._pools.get(pool_id)

    def push_kv(self, worker_id: str, data: bytes) -> None:
        self._push(f"kv:{worker_id}", data)

    def pop_kv(self, worker_id: str, timeout: float) -> Optional[bytes]:
        return self._pop(f"kv:{worker_id}", timeout)

    def kv_depth(self, worker_id: str) -> int:
        with self._meta:
            q = self._queues.get(f"kv:{worker_id}")
        return len(q.dq) if q is not None else 0

    def put_blob(self, key: str, data: bytes) -> None:
        with self._meta:
            self._blobs[key] = bytes(data)

    def get_blob(self, key: str) -> Optional[bytes]:
        with self._meta:
            return self._blobs.get(key)


class KVQueueHub(QueueHub):
    """Queues on the native kv server. Blocking pops hold a socket, so each
    hub keeps one client per calling thread (thread-local).

    Crash-survivable by construction: every thread-local client carries
    the reconnect layer (``retry_window_s``, see
    :class:`~rafiki_tpu.native.client.KVClient`), and every queue push
    mints a dedup id so the retry of a push whose ack was lost — a
    connection drop, a kvd kill -9 and supervised respawn — can never
    double-deliver. Reads and ``put_blob`` retry transparently;
    in-flight blocking pops resume on the new socket. When the window
    closes a ``ConnectionError`` surfaces and the caller degrades
    (predictor: structured 503; workers: pause the serve loop)."""

    #: default reconnect window: long enough to ride out a supervised
    #: kvd respawn + WAL replay (~1-2s observed), short enough that a
    #: truly dead data plane surfaces as a structured failure, not a
    #: hang
    RETRY_WINDOW_S = 8.0

    def __init__(self, host: str, port: int,
                 retry_window_s: Optional[float] = None) -> None:
        self._host, self._port = host, port
        self.retry_window_s = (self.RETRY_WINDOW_S
                               if retry_window_s is None
                               else float(retry_window_s))
        self._tl = threading.local()

    def _client(self):
        from ..native.client import KVClient

        c = getattr(self._tl, "client", None)
        if c is None:
            c = KVClient(self._host, self._port,
                         retry_window_s=self.retry_window_s)
            self._tl.client = c
        return c

    def drop_conn(self) -> None:
        """Force-close the calling thread's client socket (chaos /
        tests): the next hub op finds a dead transport and exercises
        the reconnect + idempotent-replay path."""
        c = getattr(self._tl, "client", None)
        if c is not None:
            c.drop_conn()

    @staticmethod
    def _dedup_id() -> str:
        import uuid

        return uuid.uuid4().hex

    def push_query(self, worker_id: str, data: bytes) -> None:
        self._client().lpush_dedup(f"q:queries:{worker_id}",
                                   self._dedup_id(), data)

    def pop_query(self, worker_id: str, timeout: float) -> Optional[bytes]:
        if timeout <= 0:  # non-blocking drain (BRPOP 0 means block forever)
            return self._client().rpop(f"q:queries:{worker_id}")
        got = self._client().brpop(f"q:queries:{worker_id}", timeout)
        return None if got is None else got[1]

    #: push-time TTL on reply queues: every reply key is mortal even
    #: when the scatter-time TTL already fired and was purged before a
    #: very late push (e.g. a worker stuck in a >30s XLA recompile
    #: inside its expiry-skew window) recreated the key
    REPLY_TTL_S = 120.0

    def push_prediction(self, query_id: str, data: bytes) -> None:
        c = self._client()
        c.lpush_dedup(f"q:preds:{query_id}", self._dedup_id(), data)
        c.expire(f"q:preds:{query_id}", self.REPLY_TTL_S)

    def pop_prediction(self, query_id: str,
                       timeout: float) -> Optional[bytes]:
        if timeout <= 0:
            return self._client().rpop(f"q:preds:{query_id}")
        got = self._client().brpop(f"q:preds:{query_id}", timeout)
        return None if got is None else got[1]

    def query_depth(self, worker_id: str) -> int:
        return self._client().llen(f"q:queries:{worker_id}")

    def discard_prediction_queue(self, query_id: str) -> None:
        self._client().delete(f"q:preds:{query_id}")

    #: stats keys expire so a DEAD worker's last counters cannot pose
    #: as current health forever (live workers republish well inside
    #: this window)
    STATS_TTL_S = 120.0

    def put_worker_stats(self, worker_id: str, stats) -> None:
        c = self._client()
        c.set(f"stats:{worker_id}", pack_message(dict(stats)))
        c.expire(f"stats:{worker_id}", self.STATS_TTL_S)

    def get_worker_stats(self, worker_id: str):
        raw = self._client().get(f"stats:{worker_id}")
        return None if raw is None else unpack_message(raw)

    def arm_reply_ttl(self, query_id: str, ttl_s: float) -> None:
        # kvd TTLs deliberately survive deletion/recreation (see
        # kv_server.cc) — one EXPIRE at scatter covers the whole
        # query lifetime including post-discard stragglers
        self._client().expire(f"q:preds:{query_id}", ttl_s)

    def put_pool_members(self, pool_id: str, members) -> None:
        # no TTL: membership is durable configuration written by the
        # (lease-fenced, single-writer) admin — a stale-looking list is
        # still the last truth; worker HEALTH stays the breakers' job
        self._client().set(f"pool:{pool_id}", pack_message(dict(members)))

    def get_pool_members(self, pool_id: str):
        raw = self._client().get(f"pool:{pool_id}")
        return None if raw is None else unpack_message(raw)

    #: KV shipments expire unconsumed: a blob whose decode worker died
    #: (or re-prefilled locally after its wait window) must not sit in
    #: the kv store forever — the decode side re-prefills token-exactly
    #: either way, so a swept shipment costs latency, never correctness
    KV_SHIP_TTL_S = 60.0

    def push_kv(self, worker_id: str, data: bytes) -> None:
        c = self._client()
        c.lpush_dedup(f"q:kv:{worker_id}", self._dedup_id(), data)
        c.expire(f"q:kv:{worker_id}", self.KV_SHIP_TTL_S)

    def pop_kv(self, worker_id: str, timeout: float) -> Optional[bytes]:
        if timeout <= 0:
            return self._client().rpop(f"q:kv:{worker_id}")
        got = self._client().brpop(f"q:kv:{worker_id}", timeout)
        return None if got is None else got[1]

    def kv_depth(self, worker_id: str) -> int:
        return self._client().llen(f"q:kv:{worker_id}")

    def put_blob(self, key: str, data: bytes) -> None:
        # durable like pool membership: a shared-prefix snapshot is
        # configuration-scale state (prefilled once per deploy)
        self._client().set(f"blob:{key}", data)

    def get_blob(self, key: str) -> Optional[bytes]:
        return self._client().get(f"blob:{key}")
