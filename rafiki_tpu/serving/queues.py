"""Query/prediction queues between the Predictor and inference workers.

Parity target: the reference's per-worker Redis lists (SURVEY.md §2
"Query/prediction queues", §3.3): the predictor pushes each query batch
onto every worker's query queue and gathers replies; workers block-pop,
predict, and push predictions back.

Two hubs, one interface: ``InProcQueueHub`` (threads in one process —
tests and the single-host fast path) and ``KVQueueHub`` (the native
``rafiki-kvd`` server — multi-process deployments). Replies land on a
per-query-id queue so the predictor can gather exactly the replicas it
scattered to, concurrently across outstanding queries.

Messages are msgpack-serialized pytrees (same codec as the ParamStore) so
query arrays cross process boundaries without JSON inflation.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, Optional

from ..store.param_store import params_from_bytes, params_to_bytes


def pack_message(msg: Dict[str, Any]) -> bytes:
    return params_to_bytes(msg)


def unpack_message(data: bytes) -> Dict[str, Any]:
    return params_from_bytes(data)


class QueueHub:
    """Scatter/gather data plane between one predictor and its workers."""

    def push_query(self, worker_id: str, data: bytes) -> None:
        raise NotImplementedError

    def pop_query(self, worker_id: str,
                  timeout: float) -> Optional[bytes]:
        raise NotImplementedError

    def push_prediction(self, query_id: str, data: bytes) -> None:
        raise NotImplementedError

    def pop_prediction(self, query_id: str,
                       timeout: float) -> Optional[bytes]:
        raise NotImplementedError

    def query_depth(self, worker_id: str) -> int:
        raise NotImplementedError

    def discard_prediction_queue(self, query_id: str) -> None:
        """Drop a query's reply queue after the gather finishes. Late
        replies (a worker answering after the deadline) would otherwise
        accumulate forever in the backing store."""
        raise NotImplementedError


class _KeyQueue:
    """One deque + its OWN condvar. A shared hub-wide condition would
    notify_all() every waiter (workers blocked on queries, predictor
    threads blocked on unrelated replies) for every push — a thundering
    herd that measurably lost to the socket-based kv hub under
    multi-client load."""

    __slots__ = ("dq", "cv", "last_used", "waiters")

    def __init__(self) -> None:
        self.dq: collections.deque = collections.deque()
        self.cv = threading.Condition()
        self.last_used = 0.0
        self.waiters = 0  # parked poppers — sweeping their entry would
        #                   orphan them (a later push notifies a NEW obj)


#: reply queues are per-query-id and transient; entries idle this long
#: with nothing queued are swept (abandoned after a gather deadline)
_IDLE_TTL_S = 120.0
_SWEEP_EVERY = 1024  # hub ops between sweeps


class InProcQueueHub(QueueHub):
    def __init__(self) -> None:
        self._queues: Dict[str, _KeyQueue] = {}
        self._meta = threading.Lock()  # guards the key → queue dict
        self._ops = 0

    def _get(self, key: str) -> _KeyQueue:
        import time

        with self._meta:
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = _KeyQueue()
            q.last_used = time.monotonic()
            self._ops += 1
            if self._ops % _SWEEP_EVERY == 0:
                cutoff = q.last_used - _IDLE_TTL_S
                dead = [k for k, v in self._queues.items()
                        if not v.waiters and v.last_used < cutoff
                        # reply queues (p:*) expire even NON-empty: a
                        # late push after discard recreates the entry
                        # and nothing would ever pop it
                        and (not v.dq or k.startswith("p:"))]
                for k in dead:  # e.g. replies that arrived after their
                    del self._queues[k]  # query's gather deadline
            return q

    def _push(self, key: str, data: bytes) -> None:
        q = self._get(key)
        with q.cv:
            q.dq.append(data)
            q.cv.notify()

    def _pop(self, key: str, timeout: float) -> Optional[bytes]:
        q = self._get(key)
        with q.cv:
            q.waiters += 1
            try:
                ok = q.cv.wait_for(lambda: bool(q.dq), timeout=timeout)
            finally:
                q.waiters -= 1
            if not ok:
                return None
            return q.dq.popleft()

    def push_query(self, worker_id: str, data: bytes) -> None:
        self._push(f"q:{worker_id}", data)

    def pop_query(self, worker_id: str, timeout: float) -> Optional[bytes]:
        return self._pop(f"q:{worker_id}", timeout)

    def push_prediction(self, query_id: str, data: bytes) -> None:
        self._push(f"p:{query_id}", data)

    def pop_prediction(self, query_id: str,
                       timeout: float) -> Optional[bytes]:
        return self._pop(f"p:{query_id}", timeout)

    def query_depth(self, worker_id: str) -> int:
        with self._meta:
            q = self._queues.get(f"q:{worker_id}")
        return len(q.dq) if q is not None else 0

    def discard_prediction_queue(self, query_id: str) -> None:
        with self._meta:
            q = self._queues.get(f"p:{query_id}")
            if q is not None and not q.waiters:
                del self._queues[f"p:{query_id}"]


class KVQueueHub(QueueHub):
    """Queues on the native kv server. Blocking pops hold a socket, so each
    hub keeps one client per calling thread (thread-local)."""

    def __init__(self, host: str, port: int) -> None:
        self._host, self._port = host, port
        self._tl = threading.local()

    def _client(self):
        from ..native.client import KVClient

        c = getattr(self._tl, "client", None)
        if c is None:
            c = KVClient(self._host, self._port)
            self._tl.client = c
        return c

    def push_query(self, worker_id: str, data: bytes) -> None:
        self._client().lpush(f"q:queries:{worker_id}", data)

    def pop_query(self, worker_id: str, timeout: float) -> Optional[bytes]:
        if timeout <= 0:  # non-blocking drain (BRPOP 0 means block forever)
            return self._client().rpop(f"q:queries:{worker_id}")
        got = self._client().brpop(f"q:queries:{worker_id}", timeout)
        return None if got is None else got[1]

    def push_prediction(self, query_id: str, data: bytes) -> None:
        self._client().lpush(f"q:preds:{query_id}", data)

    def pop_prediction(self, query_id: str,
                       timeout: float) -> Optional[bytes]:
        if timeout <= 0:
            return self._client().rpop(f"q:preds:{query_id}")
        got = self._client().brpop(f"q:preds:{query_id}", timeout)
        return None if got is None else got[1]

    def query_depth(self, worker_id: str) -> int:
        return self._client().llen(f"q:queries:{worker_id}")

    def discard_prediction_queue(self, query_id: str) -> None:
        self._client().delete(f"q:preds:{query_id}")
